"""Load-aware generation router + admission controller.

FLEETSIM_r01 measured the single-server failure mode this module
exists for: open-loop arrivals past the queueing knee drive ttft p99
from 7.9ms to 652.6ms — the queue manufactures latency while
throughput stays flat. Two levers fix the curve, both applied HERE,
ahead of any engine queue:

- **spread** — ``/generate`` goes to the least-loaded healthy backend,
  scored on the same signals the fleet heartbeat already carries
  (queue depth, active slots, ``ttft_ms_p95`` / ``tpot_ms_p95``).
  Backends serving the majority base revision are preferred so a
  mid-swap straggler doesn't answer with a stale model.
- **shed** — when every admissible backend sits at its queue bound the
  router answers ``429`` + ``Retry-After`` immediately instead of
  queueing the caller into the knee. An open-loop client that backs
  off is strictly better than one that waits: the p99 of ADMITTED
  requests stays near the service floor, and the shed count is an
  honest overload meter (``router.shed``).
- **phase-aware disaggregation** — backends declare a worker class in
  ``/healthz`` (``phase=prefill|decode|unified``). When both a
  prefill-phase and a decode-phase backend are admissible, a request
  routes as TWO legs: ``/prefill`` on the prefill worker (returns the
  content-addressed KV manifest ref + the first-token decision), then
  ``/generate`` on the decode worker carrying the ref
  (engine/kv_transfer.py moves the pages). Either class unhealthy,
  overloaded, or mid-flight failing ⇒ the classic unified route — a
  mixed old/new fleet keeps serving with no flag day, the same
  negotiation posture as wire v2.

The router is deliberately thin: stdlib HTTP in, ``urllib`` out, state
refreshed from each backend's ``/healthz`` (the same JSON the serving
frontend exports) on a poll thread. It holds no tokens, no KV, no
model — killing it loses nothing but the routing table.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import obs, reqtrace
from . import serve as _serve

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class BackendState:
    """Last-known load picture of one serving backend (from its
    ``/healthz``; ``healthy`` flips false after consecutive poll
    failures, true again on the first success)."""
    url: str
    healthy: bool = False
    queue_depth: int = 0
    active: int = 0
    tokens_per_sec: float = 0.0
    ttft_ms_p95: float = 0.0
    tpot_ms_p95: float = 0.0
    revision: str | None = None
    shed: int = 0
    spec_accept_rate: float = 0.0
    spec_k: int = 0
    # worker class (disaggregated serving): prefill | decode | unified.
    # An old backend's healthz carries no "phase" field and defaults to
    # unified — the no-flag-day negotiation: a mixed fleet keeps
    # routing every request somewhere that can serve it end to end.
    phase: str = "unified"
    kv_exported: int = 0
    kv_adopted: int = 0
    last_poll_t: float = 0.0
    consecutive_failures: int = 0

    def update(self, health: dict) -> None:
        self.healthy = bool(health.get("ok", False))
        self.queue_depth = int(health.get("queue_depth", 0))
        self.active = int(health.get("active", 0))
        self.tokens_per_sec = float(health.get("tokens_per_sec", 0.0))
        self.ttft_ms_p95 = float(health.get("ttft_ms_p95", 0.0))
        self.tpot_ms_p95 = float(health.get("tpot_ms_p95", 0.0))
        self.revision = health.get("revision")
        self.shed = int(health.get("shed", 0))
        self.spec_accept_rate = float(health.get("spec_accept_rate", 0.0))
        self.spec_k = int(health.get("spec_k", 0))
        phase = health.get("phase", "unified")
        self.phase = phase if phase in ("prefill", "decode", "unified") \
            else "unified"
        self.kv_exported = int(health.get("kv_exported", 0))
        self.kv_adopted = int(health.get("kv_adopted", 0))
        self.consecutive_failures = 0
        self.last_poll_t = time.monotonic()

    @property
    def speed_factor(self) -> float:
        """Tokens emitted per decode step: 1 for a plain backend,
        ``1 + accept_rate * K`` for a speculating one (each verify pass
        commits the accepted draft prefix plus the target's own pick).
        Defaults keep non-speculating fleets at exactly 1.0."""
        return 1.0 + max(0.0, self.spec_accept_rate) * max(0, self.spec_k)


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Pure routing decision — separated from the HTTP plumbing so the
    fleetsim load phase and the unit tests exercise the exact policy
    the live router runs.

    ``max_queue_depth`` is the admission bound PER BACKEND: a backend
    with ``queue_depth + active`` work items at or past it is
    overloaded and not admissible. When every live backend is
    overloaded the verdict is shed (429), which is the whole point —
    bounded queues are what keep admitted-request ttft off the
    collapse curve."""

    max_queue_depth: int = 6
    shed_ttft_ms: float = 0.0    # >0: also shed on backend p95 above this
    prefer_revision: bool = True

    def overloaded(self, b: BackendState) -> bool:
        if b.queue_depth + b.active >= self.max_queue_depth > 0:
            return True
        if self.shed_ttft_ms > 0 and b.ttft_ms_p95 > self.shed_ttft_ms:
            return True
        return False

    def score(self, b: BackendState) -> float:
        """Lower is better: outstanding work dominates, observed
        latency percentiles break ties between equally-queued
        backends (a slow backend at depth 2 loses to a fast one).
        Outstanding work is divided by the backend's speculative
        speed factor — a drafting backend accepting 3 of 4 proposals
        drains its queue ~4x faster, so the same depth costs less.
        Non-speculating backends have factor 1.0 (score unchanged)."""
        return ((b.queue_depth + b.active) / b.speed_factor
                + (b.ttft_ms_p95 + b.tpot_ms_p95) / 100.0)

    def choose(self, backends: list[BackendState]) -> BackendState | None:
        """Pick the backend for one request, or None ⇒ shed."""
        live = [b for b in backends if b.healthy]
        if not live:
            return None
        pool = live
        if self.prefer_revision and len(live) > 1:
            revs = [b.revision for b in live if b.revision is not None]
            if revs:
                # majority revision wins; deterministic tie-break
                pref = max(set(revs), key=lambda r: (revs.count(r), r))
                on_pref = [b for b in live if b.revision == pref]
                # ...but never shed while an off-revision backend has room
                if any(not self.overloaded(b) for b in on_pref):
                    pool = on_pref
        admit = [b for b in pool if not self.overloaded(b)]
        if not admit:
            return None
        return min(admit, key=lambda b: (self.score(b), b.url))

    def retry_after(self, backends: list[BackendState]) -> float:
        """Seconds a shed caller should back off: the least-loaded
        backend's queue drained at its observed token rate."""
        live = [b for b in backends if b.healthy]
        if not live:
            return 1.0
        b = min(live, key=self.score)
        if b.tokens_per_sec > 0:
            est = (b.queue_depth + b.active) * 32 / b.tokens_per_sec
        else:
            est = 1.0
        return min(max(est, 1.0), 30.0)


class RouterHTTPFrontend:
    """HTTP router over N serving backends.

    - ``POST /generate`` — forwarded verbatim to the policy's chosen
      backend; on backend error / 429 / 503 the next-best backend is
      tried once before giving up. Policy shed ⇒ ``429`` +
      ``Retry-After`` without touching any backend.
    - ``GET /healthz`` — router's own view: per-backend states plus
      routed/shed counters.

    Backend states refresh on a daemon poll thread (``router-poll``);
    tests can drive :meth:`refresh` synchronously instead. Registered
    with the serve module's live-frontend set so the conftest socket
    guard closes leaked routers the same way it closes leaked serving
    frontends.
    """

    def __init__(self, backend_urls: list[str], port: int = 0, *,
                 host: str = "127.0.0.1",
                 policy: RouterPolicy | None = None,
                 poll_interval_s: float = 1.0,
                 unhealthy_after: int = 3,
                 timeout_s: float = 120.0,
                 retry_after_cap_s: float = 0.25):
        if not backend_urls:
            raise ValueError("router needs at least one backend url")
        self.backends = [BackendState(url=u.rstrip("/"))
                         for u in backend_urls]
        self.policy = policy or RouterPolicy()
        self.host = host
        self.port = port
        self.poll_interval_s = poll_interval_s
        self.unhealthy_after = unhealthy_after
        self.timeout_s = timeout_s
        # how long the router is willing to honor a backend's
        # Retry-After before the next-best retry (0 disables the wait);
        # tests monkeypatch _sleep to observe without stalling
        self.retry_after_cap_s = retry_after_cap_s
        self._sleep = time.sleep
        self.routed = 0
        self.shed = 0
        self.retry_after_honored = 0
        self.disagg_routed = 0      # completed prefill->decode routes
        self.disagg_fallbacks = 0   # two-leg attempts that fell back
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # -- backend state ------------------------------------------------------
    def refresh(self) -> None:
        """One poll sweep over every backend's ``/healthz``."""
        for b in self.backends:
            try:
                with urllib.request.urlopen(b.url + "/healthz",
                                            timeout=2.0) as r:
                    health = json.loads(r.read().decode())
                with self._lock:
                    b.update(health)
            except (urllib.error.URLError, OSError, ValueError):
                with self._lock:
                    b.consecutive_failures += 1
                    if b.consecutive_failures >= self.unhealthy_after:
                        b.healthy = False

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.refresh()
            except Exception:
                logger.exception("router poll sweep failed")

    # -- routing ------------------------------------------------------------
    def _fetch_json(self, backend: BackendState, path: str,
                    body: bytes, request_id: str) -> dict:
        """POST one leg to one backend under optimistic in-flight
        accounting (the same active+=1 discipline the unified loop
        uses, so concurrent routes between health polls spread)."""
        with self._lock:
            backend.active += 1
        try:
            req = urllib.request.Request(
                backend.url + path, data=body,
                headers={"Content-Type": "application/json",
                         reqtrace.REQUEST_ID_HEADER: request_id})
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read().decode())
        finally:
            with self._lock:
                backend.active = max(0, backend.active - 1)

    def _route_disagg(self, body: bytes, request_id: str,
                      rid_hdr: dict) -> tuple[int, dict, dict] | None:
        """The two-leg disaggregated route: prefill leg on a
        prefill-phase worker (``/prefill`` → kv_ref + first_token),
        decode leg on a decode-phase worker (``/generate`` with the
        manifest ref merged into the body). Returns the completed
        response, or None ⇒ fall back to the unified loop — taken
        whenever EITHER class has no admissible backend or either leg
        fails for any reason (the fallback matrix: mixed fleets, an
        unhealthy class, a failed export, a mid-flight error all
        degrade to the classic single-worker route, counted)."""
        with self._lock:
            pre = self.policy.choose(
                [b for b in self.backends if b.phase == "prefill"])
            dec = self.policy.choose(
                [b for b in self.backends if b.phase == "decode"])
        if pre is None or dec is None:
            return None
        try:
            leg1 = self._fetch_json(pre, "/prefill", body, request_id)
            if not leg1.get("kv_ref") or leg1.get("first_token") is None:
                # export failed on the worker (already counted there):
                # a unified worker can still serve the request whole
                raise ValueError("prefill leg returned no kv_ref")
            payload = json.loads(body or b"{}")
            payload["kv_ref"] = leg1["kv_ref"]
            payload["first_token"] = leg1["first_token"]
            out = self._fetch_json(dec, "/generate",
                                   json.dumps(payload).encode(),
                                   request_id)
        except Exception:
            obs.count("router.disagg_fallbacks")
            with self._lock:
                self.disagg_fallbacks += 1
            logger.info("disaggregated route failed (prefill=%s "
                        "decode=%s); falling back to unified",
                        pre.url, dec.url, exc_info=True)
            return None
        with self._lock:
            self.routed += 1
            self.disagg_routed += 1
        obs.count("router.routed")
        obs.count("router.disagg_routed")
        out["backend"] = dec.url
        out["prefill_backend"] = pre.url
        out.setdefault("request_id", request_id)
        return 200, out, dict(rid_hdr)

    def _route(self, body: bytes,
               request_id: str | None = None) -> tuple[int, dict, dict]:
        """Forward one /generate body. Returns (code, obj, headers).

        ``request_id`` is the caller's ``X-DT-Request-Id`` (minted here
        from the body when absent — the router is the outermost
        frontend, so the identity every downstream trace stage carries
        is born at this line); it is forwarded to every backend tried
        and echoed on every outcome, including the router's own shed."""
        obs.count("router.requests")
        request_id = request_id or reqtrace.mint_request_id(body)
        rid_hdr = {reqtrace.REQUEST_ID_HEADER: request_id}
        routed = self._route_disagg(body, request_id, rid_hdr)
        if routed is not None:
            return routed
        with self._lock:
            # unified / fallback leg: prefill-phase workers cannot
            # serve /generate end to end, everything else can (a
            # decode worker degrades to local prefill)
            states = [b for b in self.backends if b.phase != "prefill"]
            chosen = self.policy.choose(states)
        tried: set[str] = set()
        while chosen is not None:
            tried.add(chosen.url)
            with self._lock:
                # optimistic in-flight accounting so concurrent routes
                # between health polls don't all pile onto one backend
                chosen.active += 1
            try:
                req = urllib.request.Request(
                    chosen.url + "/generate", data=body,
                    headers={"Content-Type": "application/json",
                             reqtrace.REQUEST_ID_HEADER: request_id})
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout_s) as r:
                        out = json.loads(r.read().decode())
                finally:
                    with self._lock:
                        chosen.active = max(0, chosen.active - 1)
                with self._lock:
                    self.routed += 1
                obs.count("router.routed")
                out["backend"] = chosen.url
                out.setdefault("request_id", request_id)
                return 200, out, dict(rid_hdr)
            except urllib.error.HTTPError as e:
                code = e.code
                try:
                    payload = json.loads(e.read().decode())
                except Exception:
                    payload = {"error": str(e)}
                if code not in (429, 503):
                    # backend answered with a real verdict (400/504/...):
                    # relay it, retrying elsewhere would double-generate
                    return code, payload, dict(rid_hdr)
                obs.count("router.backend_errors")
                retry_after = (e.headers or {}).get("Retry-After")
                with self._lock:
                    # the backend told us it is saturated; trust it
                    # until the next poll sweep says otherwise
                    chosen.queue_depth = max(chosen.queue_depth,
                                             self.policy.max_queue_depth)
                if retry_after is not None and self.retry_after_cap_s > 0:
                    # honor the backend's own back-pressure signal
                    # before piling onto the next-best backend — capped,
                    # so one saturated server never stalls the router
                    try:
                        wait = min(float(retry_after),
                                   self.retry_after_cap_s)
                    except ValueError:
                        wait = 0.0
                    if wait > 0:
                        with self._lock:
                            self.retry_after_honored += 1
                        obs.count("router.retry_after_honored")
                        self._sleep(wait)
            except (urllib.error.URLError, OSError, ValueError):
                obs.count("router.backend_errors")
                with self._lock:
                    chosen.consecutive_failures += 1
                    if chosen.consecutive_failures >= self.unhealthy_after:
                        chosen.healthy = False
            with self._lock:
                remaining = [b for b in self.backends
                             if b.url not in tried
                             and b.phase != "prefill"]
                chosen = self.policy.choose(remaining)
        with self._lock:
            self.shed += 1
            retry = self.policy.retry_after(list(self.backends))
        obs.count("router.shed")
        return 429, {"error": "all backends overloaded",
                     "retry_after_s": retry,
                     "request_id": request_id}, \
            {"Retry-After": str(max(1, int(retry))), **rid_hdr}

    # -- http ---------------------------------------------------------------
    def start(self) -> int:
        if self._server is not None:
            return self.port
        fe = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("router_http: " + fmt, *args)

            def _send(self, code: int, obj,
                      headers: dict | None = None) -> None:
                body = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.split("?", 1)[0] == "/healthz":
                    with fe._lock:
                        out = {
                            "ok": True, "role": "router",
                            "routed": fe.routed, "shed": fe.shed,
                            "retry_after_honored":
                                fe.retry_after_honored,
                            "disagg_routed": fe.disagg_routed,
                            "disagg_fallbacks": fe.disagg_fallbacks,
                            "backends": [dataclasses.asdict(b)
                                         for b in fe.backends]}
                    self._send(200, out)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if self.path.split("?", 1)[0] != "/generate":
                    self._send(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) or b"{}"
                code, obj, headers = fe._route(
                    body, self.headers.get(reqtrace.REQUEST_ID_HEADER))
                self._send(code, obj, headers)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"router-http-{self.port}",
                                        daemon=True)
        self._thread.start()
        self.refresh()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="router-poll", daemon=True)
        self._poller.start()
        _serve._LIVE_FRONTENDS.add(self)
        logger.info("routing /generate across %d backends on http://%s:%d",
                    len(self.backends), self.host, self.port)
        return self.port

    @property
    def running(self) -> bool:
        return self._server is not None

    def close(self) -> None:
        self._stop.set()
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        poller, self._poller = self._poller, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        if poller is not None:
            poller.join(timeout=5.0)
        _serve._LIVE_FRONTENDS.discard(self)
