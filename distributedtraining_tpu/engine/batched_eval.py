"""Batched cohort evaluation: score K candidate param-trees per eval pass.

The validator's hot loop was O(miners x eval_batches) *sequential* device
programs — one full eval pass per miner, each batch read, placed, and
dispatched once per miner (engine/validate.py score_miner). This module
amortizes the replicated work across a stacked **candidate axis**: K
screened deltas are stacked into one pytree with a leading [K] dim (the
same layout ``delta.stack_deltas`` gives the averager's miner axis), and
ONE jitted program computes ``eval(base + stacked[k], batch)`` for every
k per eval batch. Eval batches are read and placed once per cohort
instead of once per miner and the dispatch count drops K-fold; the same
evaluator serves GeneticMerge's population eval (engine/average.py),
which otherwise pays population x generations sequential passes.

Two spellings, chosen by the engine's mesh:

- single device: ``jax.vmap`` over the candidate axis — one fused XLA
  program whose peak memory is K x (params + activations) of the eval
  batch, which is why cohorts are bounded (see BUCKETS).
- mesh: an explicit ``shard_map`` with the CANDIDATE axis sharded over
  the mesh's largest axis (``parallel.collectives.merge_axis``, the same
  axis the averager ingest-shards miners over) — the K x param stack
  shards across devices instead of replicating, each device evaluates
  its local candidates on a replicated batch, and the per-candidate
  totals all-gather at the end. HLO-checked by
  tests/test_batched_eval.py (mirroring
  test_parameterized_mesh_merge_lowers_to_allreduce). The base rides
  replicated into the program: candidate-data-parallelism trades the
  base's fsdp sharding for K-way throughput, so this spelling targets
  eval meshes whose base fits per-device.

Cohorts are zero-padded to bucket sizes (1/2/4/8/16, then multiples of
16) to bound recompiles — a fleet whose miner count wobbles between 5
and 8 hits ONE compiled program, not four. Padded slots evaluate
``base + 0`` (harmless, slightly wasteful); compiled programs are cached
per bucket, mirroring ``ParameterizedMerge._step_cache``. The base model
itself can be folded into slot 0 (``include_base=True``) so a base
refresh re-eval rides the same cached program family as miner scoring.

In front of the evaluator, ``stage_cohorts`` is the fetch/eval pipeline:
a bounded background stager (data/prefetch.py's PrefetchIterator
pattern) runs transport fetch + wire_in + screen_delta of cohort n+1
while the device evaluates cohort n. Multi-host pods must NOT pipeline:
every staged fetch is a coordinator-read + broadcast collective
(fetch_delta_any_broadcast), and collectives issued from a background
thread would interleave nondeterministically with the eval program's —
callers pass ``pipeline=False`` there and only the single-host paths
overlap.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import delta as delta_lib
from ..utils import devprof, obs

# (base, stacked, batch) -> the stacked candidate axis: the bucket the
# jit executable cache keys this dispatch's compiled variant on
_cohort_bucket = lambda a, kw: jax.tree_util.tree_leaves(a[1])[0].shape[0]

logger = logging.getLogger(__name__)

Params = Any

# bucket ladder for cohort padding: recompiles are bounded to
# len(BUCKETS) + (cohorts beyond 16 pad to multiples of 16)
BUCKETS = (1, 2, 4, 8, 16)


def _timed_compile(fn, *args):
    """Call ``fn`` (a jitted program on fresh shapes) and record its
    first-dispatch wall time into the ``compile.ms`` registry histogram —
    the compile-COST half of the recompile counters (which only count
    occurrences). jit compiles synchronously at dispatch, so this wall
    time is trace+compile plus one async dispatch."""
    t0 = time.perf_counter()
    out = fn(*args)
    obs.observe("compile.ms", (time.perf_counter() - t0) * 1e3)
    return out


class BatchedCohortEvaluator:
    """Owns the per-bucket jitted cohort-eval programs for one engine."""

    def __init__(self, engine, *, buckets: Sequence[int] = BUCKETS,
                 prefer_compiled: bool = False):
        bs = tuple(sorted(set(int(b) for b in buckets)))
        if not bs or bs[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.engine = engine
        self.buckets = bs
        # remediation's elastic-cohort discipline (engine/remediate.py):
        # when the ladder bucket for k is NOT yet compiled but a larger
        # one is, pad up to the compiled bucket instead of compiling the
        # exact fit — a fleet whose healthy count wobbles then reuses one
        # program (padding waste) rather than walking the ladder through
        # fresh multi-second compiles (compile storm)
        self.prefer_compiled = prefer_compiled
        # ONE jitted callable, built lazily; jax.jit's executable cache
        # keys on the padded stack's shapes, so the bucket ladder bounds
        # the compile count (the ParameterizedMerge._step_cache
        # discipline: base/stacked/batch flow as ARGUMENTS so an
        # ingest-sharded stack keeps its sharding and rounds reuse the
        # compiled programs instead of retracing the model forward)
        self._jitted: Callable | None = None
        # jitted stack+pad programs keyed (n_real, k_pad, include_base):
        # the naive per-leaf jnp.stack spelling costs one dispatch per
        # PARAM TENSOR per cohort (~3x the eval pass itself at small K,
        # measured on CPU); fusing assembly into one program per bucket
        # makes cohort staging a single dispatch
        self._stack_cache: dict[tuple, Callable] = {}
        # bucket sizes this evaluator has dispatched: a NEW k_pad means a
        # fresh XLA compile of the cohort program (jit keys on the padded
        # stack's shapes) — val.cohort_bucket_compiles counts them, so a
        # wobbling fleet size that defeats the bucket ladder shows up in
        # the registry instead of as mystery multi-second eval stalls
        self._buckets_seen: set[int] = set()

    # -- bucket policy ------------------------------------------------------
    def bucket_for(self, k: int) -> int:
        """Padded cohort size for ``k`` real candidates: the smallest
        bucket >= k (multiples of the top bucket beyond it), rounded up
        to a multiple of the mesh's merge axis so the candidate axis
        shards evenly."""
        if k < 1:
            raise ValueError(f"cohort must hold >= 1 candidate, got {k}")
        for b in self.buckets:
            if k <= b:
                target = b
                break
        else:
            big = self.buckets[-1]
            target = ((k + big - 1) // big) * big
        mesh = getattr(self.engine, "mesh", None)
        if mesh is not None:
            n = mesh.shape[self._axis(mesh)]
            target = ((target + n - 1) // n) * n
        if self.prefer_compiled and target not in self._buckets_seen:
            # compiled buckets satisfied any mesh rounding when they were
            # first dispatched, so they stay valid targets here
            bigger = sorted(b for b in self._buckets_seen if b >= target)
            if bigger:
                target = bigger[0]
        return target

    def compiled_buckets(self) -> frozenset:
        """Bucket sizes with a compiled cohort program (the elastic-cohort
        chooser in engine/remediate.py prefers these)."""
        return frozenset(self._buckets_seen)

    @staticmethod
    def _axis(mesh) -> str:
        from ..parallel.collectives import merge_axis
        return merge_axis(mesh)

    def _loss_fn(self):
        """The engine's PLAIN task loss (no fused shard_map, no ambient
        mesh/rules — see TrainEngine._plain_task_loss): nested sharding
        machinery inside the candidate-sharded program would fight it.
        Fused-loss engines therefore score through the unfused spelling
        here — identical math to fp tolerance (the fused CE is pinned to
        the dense oracle)."""
        fn = getattr(self.engine, "_plain_task_loss", None)
        if fn is None:  # engines predating the attribute / test doubles
            from .train import _default_lm_loss
            fn = _default_lm_loss
        return fn

    # -- programs -----------------------------------------------------------
    def _program(self) -> Callable:
        if self._jitted is None:
            mesh = getattr(self.engine, "mesh", None)
            self._jitted = (self._build_mesh(mesh) if mesh is not None
                            else self._build_single())
        return self._jitted

    def _candidate_eval(self):
        """(stacked_delta_slice, base, batch) -> ([k] loss sums, [k] token
        counts) — the vmapped core shared by both spellings. The delta
        upcasts into the base's dtype exactly like weighted_merge, so a
        bf16 wire cohort cannot drag candidate params to bf16."""
        model = self.engine.model
        loss = self._loss_fn()

        def one(d, base, batch):
            cand = jax.tree_util.tree_map(
                lambda b, x: b + x.astype(b.dtype), base, d)
            l, t = loss(model, cand, batch)
            return l * t, t  # token-weighted, like TrainEngine.eval_step

        return jax.vmap(one, in_axes=(0, None, None))

    def _build_single(self) -> Callable:
        vmapped = self._candidate_eval()

        def eval_k(base, stacked, batch):
            return vmapped(stacked, base, batch)

        return devprof.wrap("eval.cohort", jax.jit(eval_k),
                            bucket=_cohort_bucket)

    def _build_mesh(self, mesh) -> Callable:
        from jax.sharding import PartitionSpec as P
        try:  # jax >= 0.8 top-level API, experimental path as fallback
            from jax import shard_map as _shard_map
        except ImportError:  # pragma: no cover
            from jax.experimental.shard_map import shard_map as _shard_map

        axis = self._axis(mesh)
        vmapped = self._candidate_eval()

        def local_eval(base, stacked, batch):
            # stacked arrives as each device's [k_pad / axis_size, ...]
            # shard; base and batch replicate. The all-gather at the end
            # is the program's ONLY collective — per-candidate totals are
            # scalars, so it is ~free next to the model forward.
            ls, ts = vmapped(stacked, base, batch)
            return (jax.lax.all_gather(ls, axis, tiled=True),
                    jax.lax.all_gather(ts, axis, tiled=True))

        specs = dict(mesh=mesh, in_specs=(P(), P(axis), P()),
                     out_specs=(P(), P()))
        try:
            # the replication the trailing all-gather establishes is not
            # statically inferable, so the rep check must be off (the
            # kwarg is check_rep on jax<=0.4.x, check_vma after the
            # shard_map promotion to the top-level API)
            fn = _shard_map(local_eval, check_rep=False, **specs)
        except TypeError:  # pragma: no cover — newer jax spelling
            fn = _shard_map(local_eval, check_vma=False, **specs)
        return devprof.wrap("eval.cohort", jax.jit(fn),
                            bucket=_cohort_bucket)

    # -- cohort assembly ----------------------------------------------------
    def _zeros_delta_host(self) -> Params:
        """Host zeros tree in the engine's INTERNAL param layout — the
        base's slot-0 delta and the bucket padding filler."""
        return jax.tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype),
            self.engine.abstract_params())

    def stack_cohort(self, deltas: Sequence[Params], *,
                     include_base: bool = False) -> tuple[Params, int]:
        """Host delta trees -> one candidate-stacked device tree padded to
        the bucket size (candidate-sharded on a mesh). Returns
        (stacked, k_real); slot 0 is the zero delta (== the base) when
        ``include_base``."""
        k_real = len(deltas) + (1 if include_base else 0)
        k_pad = self.bucket_for(k_real)

        mesh = getattr(self.engine, "mesh", None)
        if mesh is not None:
            zeros = (self._zeros_delta_host()
                     if include_base or k_pad > len(deltas) else None)
            cohort = ([zeros] if include_base else []) + list(deltas)
            cohort = cohort + [zeros] * (k_pad - len(cohort))
            from jax.sharding import NamedSharding, PartitionSpec as P
            axis = self._axis(mesh)

            def stack_leaf(*xs):
                stacked = np.stack([np.asarray(jax.device_get(x))
                                    for x in xs], axis=0)
                spec = P(axis, *([None] * (stacked.ndim - 1)))
                return jax.device_put(stacked, NamedSharding(mesh, spec))

            return jax.tree_util.tree_map(stack_leaf, *cohort), k_real

        if not deltas:
            # include_base with no candidates (the base-refresh re-eval):
            # nothing real to stack, so the zeros skeleton seeds slot 0
            cohort = [self._zeros_delta_host()] * k_pad
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *cohort), k_real

        key = (len(deltas), k_pad, include_base)
        assemble = self._stack_cache.get(key)
        if assemble is None:
            obs.count("val.cohort_stack_compiles")
            lead = 1 if include_base else 0

            def assemble(*real):
                def leaf(*xs):
                    s = jnp.stack(xs, axis=0)
                    front = jnp.zeros((lead,) + s.shape[1:], s.dtype)
                    back = jnp.zeros((k_pad - lead - s.shape[0],)
                                     + s.shape[1:], s.dtype)
                    return jnp.concatenate([front, s, back], axis=0)

                return jax.tree_util.tree_map(leaf, *real)

            assemble = self._stack_cache[key] = devprof.wrap(
                "eval.stack", jax.jit(assemble), bucket=k_pad)
            return _timed_compile(assemble, *deltas), k_real
        return assemble(*deltas), k_real

    def _place_batch(self, batch: dict) -> dict:
        mesh = getattr(self.engine, "mesh", None)
        if mesh is None:
            return self.engine.place_batch(batch)
        # REPLICATED, not dp-sharded: the mesh's parallel axis carries
        # candidates in this program, so every device reads the full batch
        from jax.sharding import NamedSharding, PartitionSpec as P
        s = NamedSharding(mesh, P())
        spans = getattr(self.engine, "_mesh_spans_processes", None)
        if spans is not None and spans():
            return {k: jax.make_array_from_process_local_data(
                        s, np.asarray(v)) for k, v in batch.items()}
        return {k: jax.device_put(np.asarray(v), s)
                for k, v in batch.items()}

    # -- evaluation ---------------------------------------------------------
    def evaluate_stacked(self, base: Params, stacked: Params, k_real: int,
                         batches: Iterable[dict]
                         ) -> list[tuple[float, float]]:
        """Per-candidate (mean loss, perplexity) for the first ``k_real``
        slots of an already-stacked candidate-delta tree (padded here to
        the bucket if needed). Accumulation stays on device — ONE host
        sync per cohort, not per candidate or per batch (the same
        discipline as TrainEngine.evaluate)."""
        k_stack = delta_lib.miner_axis_size(stacked)
        k_pad = self.bucket_for(max(k_stack, k_real))
        fresh_bucket = k_pad not in self._buckets_seen
        if fresh_bucket:
            self._buckets_seen.add(k_pad)
            obs.count("val.cohort_bucket_compiles")
        if k_stack != k_pad:
            pad = self._stack_cache.get(("pad", k_pad))
            if pad is None:  # one program, not one concat dispatch per leaf
                pad = self._stack_cache[("pad", k_pad)] = devprof.wrap(
                    "eval.pad",
                    jax.jit(lambda s: delta_lib.pad_stack(s, k_pad)),
                    bucket=k_pad)
                stacked = _timed_compile(pad, stacked)
            else:
                stacked = pad(stacked)
        prog = self._program()
        total = count = None
        for batch in batches:
            placed = self._place_batch(batch)
            if fresh_bucket:
                # the counter above says a compile HAPPENED; this says
                # what it COST — first-dispatch wall time (trace+compile;
                # the jitted call returns before execution finishes, so
                # device time stays out). compile.ms across all sites is
                # what makes a compile storm visible in the fleet report.
                l, t = _timed_compile(prog, base, stacked, placed)
                fresh_bucket = False
            else:
                l, t = prog(base, stacked, placed)
            total = l if total is None else total + l
            count = t if count is None else count + t
        if count is None:
            return [(float("nan"), float("nan"))] * k_real
        total = np.asarray(jax.device_get(total), np.float64)
        count = np.asarray(jax.device_get(count), np.float64)
        out = []
        for i in range(k_real):
            if count[i] == 0:
                out.append((float("nan"), float("nan")))
            else:
                mean = total[i] / count[i]
                out.append((float(mean), float(np.exp(mean))))
        return out

    def evaluate_cohort(self, base: Params, deltas: Sequence[Params],
                        batches: Iterable[dict], *,
                        include_base: bool = False
                        ) -> list[tuple[float, float]]:
        """Score a cohort of host delta trees against ``base`` in one
        program per eval batch. With ``include_base`` the first returned
        entry is the BASE's (loss, ppl) — a zero delta in slot 0, so a
        base-refresh re-eval rides the same bucket-cached program as
        miner scoring instead of a separate engine.evaluate pass."""
        if not deltas and not include_base:
            return []
        stacked, k_real = self.stack_cohort(deltas,
                                            include_base=include_base)
        return self.evaluate_stacked(base, stacked, k_real, batches)


# ---------------------------------------------------------------------------
# Fetch/eval pipelining
# ---------------------------------------------------------------------------

def stage_cohorts(items: Sequence, cohort_size: int, stage_one: Callable,
                  *, pipeline: bool = True, depth: int = 1,
                  stage_many: Callable | None = None) -> Iterator[list]:
    """Group ``items`` into cohorts of ``cohort_size`` and map
    ``stage_one`` over each — on a bounded background thread ``depth``
    cohorts ahead when ``pipeline``, so staging cohort n+1 (transport
    fetch + wire_in + screen) overlaps the caller's device eval of
    cohort n.

    ``stage_many`` (optional) stages a WHOLE cohort in one call instead
    of item-by-item — how the validator routes a cohort through the
    concurrent ingest pool (engine/ingest.py: fetches in flight at once,
    one fused screen program) rather than serial per-miner staging.

    ``pipeline=False`` stages inline in caller order — REQUIRED on
    multi-host pods, where staging contains broadcast collectives that
    must interleave deterministically with the eval program's. The
    returned iterator exposes ``close()`` when pipelined (stop the
    worker early on a failed round).
    """
    if cohort_size < 1:
        raise ValueError(f"cohort_size must be >= 1, got {cohort_size}")
    groups = [list(items[i:i + cohort_size])
              for i in range(0, len(items), cohort_size)]

    def stage_group(group):
        # stager-side occupancy half: time actually spent fetching +
        # screening (the consumer's wait half is val.stage_wait_ms in
        # engine/validate.py) — busy/(busy+wait) is pipeline overlap
        t0 = time.perf_counter()
        if stage_many is not None:
            out = stage_many(group)
        else:
            out = [stage_one(x) for x in group]
        obs.count("val.stage_busy_ms", (time.perf_counter() - t0) * 1e3)
        return out

    if not pipeline:
        return iter(stage_group(group) for group in groups)
    from ..data.prefetch import map_prefetch
    return map_prefetch(stage_group, groups, depth=depth)
