"""Validator engine.

Rebuild of ModelValidator/DeltaValidator (hivetrain/validation_logic.py):
score every miner's delta by measured loss/perplexity improvement over the
current base on a held-out shard, normalize, emit to the chain.

The functional core removes the reference's most fragile machinery: where it
deep-copies the model state, mutates it per miner, and restores it afterwards
(validation_logic.py:123-139), here scoring is just
``evaluate(apply_delta(base, d))`` — base params are never mutated, so there
is nothing to restore and a crash mid-round cannot corrupt the model.

Scoring rule parity (validation_logic.py:136-166):
  score = max(0, base_loss - new_loss)   [loss mode]
  score = max(0, base_ppl - new_ppl)     [perplexity mode]
  missing/invalid delta -> 0
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable, Optional

import jax

from .. import delta as delta_lib
from ..utils import obs
from ..utils.metrics import device_metrics
from .scheduler import Clock, RealClock

logger = logging.getLogger(__name__)

Params = Any


@dataclasses.dataclass
class MinerScore:
    hotkey: str
    score: float
    loss: float | None = None
    perplexity: float | None = None
    reason: str = "ok"


class Validator:
    def __init__(self, engine, transport, chain, *,
                 eval_batches: Callable[[], Iterable[dict]],
                 metric: str = "loss",          # "loss" | "perplexity"
                 max_delta_abs: float | None = 1e3,
                 clock: Clock | None = None,
                 metrics=None,
                 lora_cfg=None,
                 accept_quant: bool = True,
                 accept_wire_v2: bool = True,
                 stale_deltas: str = "accept",
                 cohort_size: int = 8,
                 pipeline_depth: int = 1,
                 ingest_workers: int = 4,
                 ingest_cache_mb: int = 2048,
                 fleet=None,
                 remediation=None,
                 base_fetcher=None):
        self.engine = engine
        # content-addressed base fetches (engine/basedist.BaseFetcher):
        # single-host base refreshes delta-pull only changed-hash layers
        # (monolithic fallback inside); None = the monolithic pull
        self.base_fetcher = base_fetcher
        # fleet health plane (engine/health.py FleetMonitor): heartbeats
        # polled per round, staging outcomes folded via the ingest
        # observer, per-miner scores recorded as the ledger's score
        # history, SLOs evaluated + ledger flushed at the round cadence
        self.fleet = fleet
        # remediation layer (engine/remediate.py): quarantined miners are
        # excluded from staging (scored 0, reason "quarantined"), their
        # chain scores decay, and the effective cohort size steps down
        # the compiled-bucket ladder when the healthy count drops
        self.remediation = remediation
        self.transport = transport
        self.chain = chain
        self.eval_batches = eval_batches
        self.metric = metric
        self.max_delta_abs = max_delta_abs
        self.clock = clock or RealClock()
        self.metrics = metrics
        # ``accept_quant=False``: fleet is known all-float — int8-wire
        # submissions are rejected instead of dequantized, and garbage
        # submissions skip the quarter-model quant-template alloc
        self.accept_quant = accept_quant
        # wire-v2 shard-manifest submissions (engine/ingest.py decodes
        # them shard-granularly); False = v1-only receiver posture
        self.accept_wire_v2 = accept_wire_v2
        # staleness policy for submissions whose rider names a superseded
        # base. Default "accept" (reference parity): scoring a stale
        # delta vs the new base is noisy but informative, EMA smooths
        # it, and zero-scoring every honest miner for one push interval
        # after each merge would be harsher than the noise. "skip"
        # zero-scores them with a named reason instead (the averager
        # defaults to skip — see AveragerLoop.stale_deltas for why the
        # MERGE must not ingest them).
        if stale_deltas not in ("skip", "accept"):
            raise ValueError(f"stale_deltas must be 'skip' or 'accept', "
                             f"got {stale_deltas!r}")
        self.stale_deltas = stale_deltas
        # Batched cohort scoring (engine/batched_eval.py): score up to
        # ``cohort_size`` screened deltas per eval pass — eval batches are
        # read/placed once per COHORT instead of once per miner, and the
        # per-round eval dispatch count drops ~cohort_size-fold.
        # ``pipeline_depth`` > 0 additionally overlaps transport fetch +
        # decode + screening of cohort n+1 with device eval of cohort n
        # (single-host only; pods stage inline to keep broadcast
        # collectives deterministic). cohort_size <= 1 restores the
        # sequential score_miner path exactly.
        if cohort_size < 0:
            raise ValueError(f"cohort_size must be >= 0, got {cohort_size}")
        self.cohort_size = cohort_size
        self.pipeline_depth = pipeline_depth
        self._cohort_eval = None
        # concurrent revision-aware ingest (engine/ingest.py): fetch pool
        # width and host-cache byte budget (0 disables the cache; 1
        # worker restores serial fetch order within a cohort)
        self.ingest_workers = ingest_workers
        self.ingest_cache_mb = ingest_cache_mb
        self._ingestor = None
        # accept adapter-tree submissions alongside full-param deltas
        # (engine/lora_train.py fetch_delta_any)
        self.lora_cfg = lora_cfg
        # cached once: the template depends only on base SHAPES, which are
        # fixed by the model config across base revisions
        self._lora_template = None

        self.base_params: Params | None = None
        self._base_revision = None
        self.base_loss: float | None = None
        self.base_ppl: float | None = None
        # per-miner contribution credit (engine/lineage.py CreditLedger):
        # each round's cohort evals fold into leave-one-out improvement
        # estimates per base revision — ONE estimate per (revision,
        # hotkey), re-validation of an unchanged base replaces rather
        # than double-counts — surfaced as dt_lineage_credit{hotkey}
        # (utils/obs_http.py) and fleet_report's credit column
        from .lineage import CreditLedger
        self.credit = CreditLedger()
        self._warned_no_permit = False
        # hotkey -> correlation id of the artifact staged THIS round (from
        # the delta's meta rider, utils/obs.py) — written by the staging
        # thread, read when tagging eval spans and the round record
        self._round_cids: dict[str, str] = {}

    # -- validator permit ---------------------------------------------------
    def has_vpermit(self, meta=None) -> bool:
        """True when this hotkey's uid holds validator stake — the reference
        gates weight-setting to permitted validators
        (btt_connector.py:358-385; --neuron.vpermit_tao_limit)."""
        get_vuids = getattr(self.chain, "get_validator_uids", None)
        if get_vuids is None:
            return True  # chain impl has no permit concept (bare stubs)
        meta = meta if meta is not None else self.chain.sync()
        try:
            uid = meta.uids[list(meta.hotkeys).index(self.chain.my_hotkey)]
        except ValueError:
            return False  # not registered on the subnet
        return uid in get_vuids()

    # -- multi-host (config 5: the validator can span a pod too) ------------
    def _multi(self) -> bool:
        from .train import mesh_spans
        return mesh_spans(self.engine)

    _host_template_cache = None

    def _host_template(self):
        """Cached WIRE-layout template: shapes are fixed by the model
        config, and rebuilding a full-model zeros tree per scored miner
        per round is O(model bytes) of pure allocation. Everything read
        from the transport validates against this and converts to the
        internal layout via wire_in (train.py wire helpers)."""
        if self._host_template_cache is None:
            from .train import host_wire_template
            self._host_template_cache = host_wire_template(self.engine)
        return self._host_template_cache

    def _broadcast_base(self, current_revision):
        from .train import broadcast_base_fetch
        return broadcast_base_fetch(self.transport, self._host_template(),
                                    current_revision)

    # -- base model ---------------------------------------------------------
    def bootstrap(self, rng=None, params=None) -> None:
        """``params`` (value or zero-arg callable, e.g. a pretrained loader)
        is used only when no base is published yet — see MinerLoop.bootstrap."""
        if self._multi():
            fetched = self._broadcast_base(None)
        elif self.transport.base_revision() is not None:
            fetched = self._fetch_base_single()
        else:
            fetched = None
        if fetched is not None:
            from .train import wire_in
            base, self._base_revision = wire_in(self.engine,
                                                fetched[0]), fetched[1]
        else:
            init = params() if callable(params) else params
            # genesis only: the one path that must materialize a full tree
            base = init if init is not None \
                else self.engine.model.init_params(
                    rng if rng is not None else jax.random.PRNGKey(0))
        self.base_params = self.engine.place_params(base)
        self._eval_base()

    def _fetch_base_single(self, revision=None):
        """Single-host base pull: content-addressed delta-pull when a
        BaseFetcher is wired (engine/basedist.py — it degrades to the
        monolithic pull internally), else the monolithic read. Torn or
        hostile reads return None, never raise (same contract as
        MinerLoop._fetch_base_single)."""
        if self.base_fetcher is not None:
            return self.base_fetcher.fetch(self._host_template(),
                                           revision=revision)
        return self.transport.fetch_base(self._host_template())

    def _evaluator(self):
        if self._cohort_eval is None:
            from .batched_eval import BatchedCohortEvaluator
            # with remediation attached, a shrunken cohort pads up to an
            # already-compiled bucket instead of compiling the exact fit
            # (the elastic-cohort anti-compile-storm rule)
            self._cohort_eval = BatchedCohortEvaluator(
                self.engine, prefer_compiled=self.remediation is not None)
        return self._cohort_eval

    def _eval_base(self) -> None:
        # full eval pass at startup/base-change (validation_logic.py:48).
        # With cohort scoring on, the base folds into slot 0 of the same
        # bucket-cached cohort program (a zero delta), so a base refresh
        # never compiles or dispatches a separate eval path.
        if self.cohort_size > 1:
            (self.base_loss, self.base_ppl), = self._evaluator(
                ).evaluate_cohort(self.base_params, [], self.eval_batches(),
                                  include_base=True)
        else:
            self.base_loss, self.base_ppl = self.engine.evaluate(
                self.base_params, self.eval_batches())
        logger.info("validator: base loss=%.4f ppl=%.2f",
                    self.base_loss, self.base_ppl)

    def _maybe_refresh_base(self) -> None:
        if self._multi():
            # per-process transport reads would hand different base trees
            # to one cross-process SPMD program — coordinator reads,
            # everyone applies the identical broadcast
            fetched = self._broadcast_base(self._base_revision)
        else:
            rev = self.transport.base_revision()
            if rev is None or rev == self._base_revision:
                return
            fetched = self._fetch_base_single(rev)
        if fetched is None:
            return
        from .train import wire_in
        self.base_params = self.engine.place_params(
            wire_in(self.engine, fetched[0]))
        self._base_revision = fetched[1]
        self._eval_base()

    # -- scoring ------------------------------------------------------------
    def _adapter_template(self):
        if self.lora_cfg is None:
            return None
        if self._lora_template is None:
            from .lora_train import adapter_template
            # WIRE layout, like every transport template: adapter trees
            # travel unrolled regardless of the publisher's scan setting
            self._lora_template = adapter_template(self._host_template(),
                                                   self.lora_cfg)
        return self._lora_template

    _quant_template_cache = None

    def _quant_template(self):
        """Cached int8 wire template, passed UNCALLED as a lazy supplier:
        an all-f32/bf16 fleet never validates against it and never pays
        the quarter-model-bytes allocation."""
        if self._quant_template_cache is None:
            from .. import delta as _dl
            self._quant_template_cache = _dl.quantized_template(
                self._host_template())
        return self._quant_template_cache

    def _ingest(self):
        """Lazy shared ingest front-end (engine/ingest.py): concurrent
        fetch pool + content-addressed host cache + fused cohort screen,
        the same subsystem the averager gathers through. Screening runs
        in WIRE layout against the wire template — the same leaves the
        old per-miner screen checked post-wire_in."""
        if self._ingestor is None:
            from .ingest import DeltaIngestor
            self._ingestor = DeltaIngestor(
                self.transport, self._host_template,
                lora_cfg=self.lora_cfg,
                lora_template=self._adapter_template,
                quant_template=self._quant_template,
                accept_quant=self.accept_quant,
                accept_wire_v2=self.accept_wire_v2,
                max_delta_abs=self.max_delta_abs,
                stale_deltas=self.stale_deltas,
                workers=self.ingest_workers,
                cache_bytes=self.ingest_cache_mb * (1 << 20),
                span_prefix="val",
                observer=(self.fleet.record_staging
                          if self.fleet is not None else None))
        return self._ingestor

    def close(self) -> None:
        """Drop the ingest pool's worker threads (idempotent)."""
        if self._ingestor is not None:
            self._ingestor.close()
        if self.fleet is not None:
            self.fleet.close()

    def _stage_many(self, hotkeys):
        """Fetch + screen a cohort of submissions through the shared
        ingest subsystem — concurrent fetches, per-miner revision cache,
        one fused screen program for the cohort. Returns
        ``[(hotkey, delta|None, reason), ...]`` in input order.

        Correlation: the artifact's ``delta_id`` (read from the meta
        rider during staging) tags the fetch/screen spans and the eval
        span later, joining this round's records to the miner's push
        spans in scripts/obs_report.py. On a pod the coordinator stages
        and broadcasts (engine/ingest.py's lockstep rule)."""
        from .train import wire_in
        staged = self._ingest().stage(list(hotkeys),
                                      base_revision=self._base_revision,
                                      multi=self._multi(),
                                      exclude=(self.remediation.is_excluded
                                               if self.remediation is not None
                                               else None))
        out = []
        for s in staged:
            if s.cid is not None:
                self._round_cids[s.hotkey] = s.cid
            d = wire_in(self.engine, s.delta) if s.delta is not None else None
            out.append((s.hotkey, d, s.reason))
        return out

    def _stage_miner(self, hotkey: str):
        """Single-miner spelling of ``_stage_many`` (the sequential
        score_miner path and ad-hoc callers)."""
        (res,) = self._stage_many([hotkey])
        return res

    def _score_from(self, hotkey: str, loss: float, ppl: float) -> MinerScore:
        if self.metric == "perplexity":
            score = max(0.0, (self.base_ppl or 0.0) - ppl)
        else:
            score = max(0.0, (self.base_loss or 0.0) - loss)
        return MinerScore(hotkey, score, loss=loss, perplexity=ppl)

    def score_miner(self, hotkey: str) -> MinerScore:
        hotkey, d, reason = self._stage_miner(hotkey)
        if d is None:
            return MinerScore(hotkey, 0.0, reason=reason)
        candidate = delta_lib.apply_delta(self.base_params, d)
        with obs.span("val.eval", cid=self._round_cids.get(hotkey),
                      miner=hotkey):
            loss, ppl = self.engine.evaluate(candidate, self.eval_batches())
        return self._score_from(hotkey, loss, ppl)

    def _score_cohorts(self, hotkeys: list[str]) -> list[MinerScore]:
        """Batched scoring: stage cohorts of ``cohort_size`` submissions
        (pipelined against device eval off-pod), then score each cohort's
        valid deltas in one stacked program per eval batch."""
        from .batched_eval import stage_cohorts
        evaluator = self._evaluator()
        pipeline = self.pipeline_depth > 0 and not self._multi()
        results: list[MinerScore] = []
        cohort = self.cohort_size
        if self.remediation is not None:
            # elastic cohort: quarantine can leave far fewer stageable
            # miners than the configured cohort — step the group size down
            # the ladder (preferring compiled buckets) so padded slots
            # shrink without a fresh compile (engine/remediate.py)
            healthy = len(self.remediation.filter_hotkeys(hotkeys))
            cohort = self.remediation.cohort_size(
                self.cohort_size, healthy,
                compiled=evaluator.compiled_buckets())
            obs.gauge("val.effective_cohort", float(cohort))
        staged = stage_cohorts(hotkeys, cohort, self._stage_miner,
                               pipeline=pipeline,
                               depth=max(self.pipeline_depth, 1),
                               stage_many=self._stage_many)
        try:
            it = iter(staged)
            while True:
                # time blocked on the stager: together with the stager's
                # own val.stage_busy_ms this reads as pipeline occupancy —
                # near-zero wait means staging fully overlaps device eval
                t0 = time.perf_counter()
                try:
                    cohort = next(it)
                except StopIteration:
                    break
                obs.observe("val.stage_wait_ms",
                            (time.perf_counter() - t0) * 1e3)
                valid = [(h, d) for h, d, _ in cohort if d is not None]
                results.extend(MinerScore(h, 0.0, reason=r)
                               for h, d, r in cohort if d is None)
                if not valid:
                    continue
                cids = [c for c in (self._round_cids.get(h)
                                    for h, _ in valid) if c]
                with obs.span("val.cohort_eval", k=len(valid), cids=cids):
                    scored = evaluator.evaluate_cohort(
                        self.base_params, [d for _, d in valid],
                        self.eval_batches())
                results.extend(self._score_from(h, loss, ppl)
                               for (h, _), (loss, ppl) in zip(valid, scored))
        finally:
            close = getattr(staged, "close", None)
            if close is not None:  # stop the stager early on a failed round
                close()
        return results

    def _synced_metagraph(self):
        """Round-start metagraph: coordinator's snapshot broadcast on a pod
        (train.broadcast_metagraph), plain sync otherwise."""
        if not self._multi():
            return self.chain.sync()
        from .train import broadcast_metagraph
        return broadcast_metagraph(self.chain)

    _round = 0

    def validate_and_score(self) -> list[MinerScore]:
        """One validation round (validate_and_score,
        validation_logic.py:99-189)."""
        self._round_cids.clear()  # correlation ids are per round
        meta = self._synced_metagraph()
        self._maybe_refresh_base()
        others = [h for h in meta.hotkeys if h != self.chain.my_hotkey]
        if self.fleet is not None and not self._multi():
            # heartbeat observation round BEFORE staging, so the staging
            # observer folds this round's outcomes into the advanced
            # round counter (pods run fleet=None off-coordinator)
            try:
                self.fleet.poll(others)
            except Exception:
                logger.exception("validator: fleet heartbeat poll failed")
        if self.cohort_size > 1:
            results = self._score_cohorts(others)
        else:
            results = [self.score_miner(h) for h in others]
        scored = {s.hotkey: s.score for s in results}
        # leave-one-out credit attribution for THIS base revision, from
        # the cohort evals just computed (engine/lineage.py); isolated —
        # attribution must never fail a scoring round
        round_credits: dict[str, float] = {}
        try:
            round_credits = self.credit.update(self._base_revision,
                                               self.base_loss, results)
        except Exception:
            logger.exception("validator: credit attribution failed")
        if self.remediation is not None:
            # quarantined miners' scores decay toward zero instead of the
            # chain EMA holding their pre-breach weight (the "scores
            # decayed" half of quarantine, engine/remediate.py)
            scored = self.remediation.decay_scores(scored)
        if self.fleet is not None:
            try:
                self.fleet.record_scores(scored)
                self.fleet.record_credit(self.credit.totals())
                breaches = self.fleet.evaluate_slos()
                if self.remediation is not None:
                    self.remediation.observe_round(breaches)
                self.fleet.flush(self.metrics, step=self._round)
            except Exception:
                logger.exception("validator: fleet round-end failed")
        # device memory watermarks as registry gauges at the round
        # cadence: the numbers the heartbeat and the exporter surface
        from ..utils.metrics import device_memory_watermarks
        for k, v in device_memory_watermarks().items():
            obs.gauge(f"device.{k}", v)
        if self.metrics:
            # BOUNDED metric-name cardinality: the reference logged
            # loss_<hotkey>/score_<hotkey> per miner — unbounded label
            # space that melts a metrics backend past a few hundred uids.
            # Here the per-round summary uses a fixed key set; the full
            # per-miner detail rides ONE structured record (JSONL keeps
            # it verbatim; MLflowSink's numeric filter drops it, keeping
            # the backend's series count constant).
            with_loss = [s for s in results if s.loss is not None]
            positive = [s for s in results if s.score > 0]
            self.metrics.log({
                **device_metrics(),
                "scored": len(results),
                "rejected": len(results) - len(with_loss),
                "score_positive": len(positive),
                "score_mean": (sum(s.score for s in results)
                               / max(len(results), 1)),
                "score_max": max((s.score for s in results), default=0.0),
                "loss_best": min((s.loss for s in with_loss),
                                 default=float("nan")),
                "base_loss": self.base_loss,
                "round_scores": {
                    s.hotkey: {"score": s.score, "loss": s.loss,
                               "reason": s.reason,
                               "cid": self._round_cids.get(s.hotkey),
                               "credit": round_credits.get(s.hotkey)}
                    for s in results},
            }, step=self._round)
            # periodic registry flush (span histograms, stage/eval timing,
            # retry counters) at the round cadence
            obs.flush(self.metrics, step=self._round)
        self._round += 1
        if self.chain.should_set_weights():
            if self.has_vpermit(meta):
                self.chain.set_weights(scored)  # EMA+normalize inside chain
            elif not self._warned_no_permit:
                self._warned_no_permit = True
                logger.warning(
                    "validator %s holds no validator permit (stake below "
                    "the vpermit limit) — scoring continues but weights "
                    "are NOT emitted", self.chain.my_hotkey)
        return results

    def run_periodic(self, *, interval: float = 1800.0,   # neurons/validator.py:112
                     rounds: int | None = None) -> int:
        """Run rounds forever (or ``rounds`` times); returns how many
        completed without an exception so callers can exit non-zero when
        every round failed."""
        done = succeeded = 0
        while rounds is None or done < rounds:
            try:
                self.validate_and_score()
                succeeded += 1
            except Exception:
                logger.exception("validation round failed; continuing")
            done += 1
            if rounds is None or done < rounds:
                self.clock.sleep(interval)
        return succeeded
