"""Ed25519 artifact envelopes: authenticity for the artifact plane.

The reference's trust anchor is HF repo ownership plus hotkey-signed metric
posts (keypair.sign verified by the receiving validator,
hivetrain/utils/dummy_miner.py:63-68). The LocalFS/registry deployments here
have no repo-ownership equivalent — any process can overwrite
``deltas/<miner>.msgpack`` — so this module supplies the missing anchor: a
detached-signature envelope over the serialized artifact bytes, verified on
fetch against the hotkey's registered public key (transport/signed.py).

Wire format (fixed-size header after a 1-byte context length):

    MAGIC(6) || ctx_len(1) || context || pubkey(32) || signature(64) || payload

The signature covers ``context || payload`` where context is a short
domain-separation string ("delta:<hotkey>" / "base:<hotkey>"). The context
travels IN the envelope so a verifier can always check the artifact *kind*
(a miner's signed delta can never be replayed as a base) even when it does
not know the expected signer; identity binding additionally requires the
caller's ``expected_context``/``expected_pub``. Unsigned payloads (no MAGIC
prefix) pass through untouched so mixed fleets keep working; whether they
are *accepted* is the transport wrapper's policy.
"""

from __future__ import annotations

from .serialization import PayloadError

# Identity (-> cryptography) is imported lazily inside wrap/unwrap: plain
# transports call strip_envelope/is_enveloped on every fetch, and those must
# work without the optional cryptography dependency installed.

MAGIC = b"DTSG2\x00"
_PUB_LEN = 32
_SIG_LEN = 64
_MAX_CTX = 255


def delta_context(hotkey: str) -> bytes:
    return b"delta:" + hotkey.encode()


def base_context(hotkey: str) -> bytes:
    return b"base:" + hotkey.encode()


def is_enveloped(data: bytes) -> bool:
    return data[:len(MAGIC)] == MAGIC


def _parse(data: bytes) -> tuple[bytes, bytes, bytes, bytes]:
    """(context, pub, sig, payload) of an enveloped blob; PayloadError on
    truncation. Pure byte slicing — no cryptography involved."""
    if len(data) < len(MAGIC) + 1:
        raise PayloadError("truncated signature envelope")
    ctx_len = data[len(MAGIC)]
    hdr_len = len(MAGIC) + 1 + ctx_len + _PUB_LEN + _SIG_LEN
    if len(data) < hdr_len:
        raise PayloadError("truncated signature envelope")
    off = len(MAGIC) + 1
    ctx = bytes(data[off:off + ctx_len])
    off += ctx_len
    pub = bytes(data[off:off + _PUB_LEN])
    off += _PUB_LEN
    sig = bytes(data[off:off + _SIG_LEN])
    return ctx, pub, sig, bytes(data[off + _SIG_LEN:])


def strip_envelope(data: bytes) -> bytes:
    """Payload bytes WITHOUT signature verification (plain transports call
    this so a node not running --sign-artifacts still *reads* a signed
    fleet's artifacts — it simply gains no authenticity from them, the same
    trust level as any unsigned artifact it accepts). Nodes that want
    verification wrap their transport in SignedTransport, whose raw-bytes
    path bypasses this."""
    if not is_enveloped(data):
        return data
    return _parse(data)[3]


def wrap(payload: bytes, identity, context: bytes) -> bytes:
    """Sign ``payload`` under ``context`` and prepend the envelope header."""
    if len(context) > _MAX_CTX:
        raise ValueError(f"context too long ({len(context)} > {_MAX_CTX})")
    sig = identity.sign(context + payload)
    assert len(identity.public_bytes) == _PUB_LEN and len(sig) == _SIG_LEN
    return (MAGIC + bytes([len(context)]) + context
            + identity.public_bytes + sig + payload)


def unwrap_with_context(data: bytes,
                        expected_context: bytes | None = None, *,
                        context_prefix: bytes | None = None,
                        kind: bytes | None = None,
                        expected_pub: bytes | None = None,
                        require: bool = False) -> tuple[bytes, bytes | None]:
    """Verify and strip the envelope -> (payload, context).

    - enveloped + valid signature (and matching ``expected_context`` /
      ``context_prefix`` / ``kind`` prefix / ``expected_pub`` when given)
      -> (payload, context)
    - enveloped but invalid/mismatched -> PayloadError (a forgery must never
      degrade to "treat as unsigned")
    - not enveloped -> (payload, None), unless ``require`` (signature policy
      is on when the hotkey has a registered key) -> PayloadError

    ``kind`` (e.g. b"base") checks only the context's domain prefix — what a
    verifier can still enforce when it does not know the signer's identity.
    ``context_prefix`` matches exactly-or-with-a-":<suffix>" (the suffix
    carries the anti-rollback sequence, transport/signed.py).
    """
    from .utils.identity import Identity

    if not is_enveloped(data):
        if require:
            raise PayloadError("unsigned payload where a signature is required")
        return data, None
    ctx, pub, sig, payload = _parse(data)
    if expected_context is not None and ctx != expected_context:
        raise PayloadError(
            f"envelope context {ctx!r} does not match expected "
            f"{expected_context!r}")
    if context_prefix is not None and ctx != context_prefix \
            and not ctx.startswith(context_prefix + b":"):
        raise PayloadError(
            f"envelope context {ctx!r} does not match expected "
            f"{context_prefix!r}")
    if kind is not None and not ctx.startswith(kind + b":"):
        raise PayloadError(
            f"envelope context {ctx!r} is not a {kind.decode()!r} artifact")
    if expected_pub is not None and pub != expected_pub:
        raise PayloadError("envelope public key does not match the hotkey's "
                           "registered key")
    try:
        signer = Identity.public_only(pub)
    except Exception as e:
        raise PayloadError(f"bad envelope public key: {e}") from e
    if not signer.verify(ctx + payload, sig):
        raise PayloadError("invalid artifact signature")
    return payload, ctx


def unwrap(data: bytes, expected_context: bytes | None = None, *,
           kind: bytes | None = None,
           expected_pub: bytes | None = None,
           require: bool = False) -> bytes:
    """See unwrap_with_context; returns the payload alone."""
    return unwrap_with_context(data, expected_context, kind=kind,
                               expected_pub=expected_pub,
                               require=require)[0]


def context_seq(ctx: bytes | None, prefix: bytes) -> int:
    """The anti-rollback sequence a context carries after ``prefix + b':'``
    (0 when absent/unsigned/malformed)."""
    if ctx is None or not ctx.startswith(prefix + b":"):
        return 0
    try:
        return int(ctx[len(prefix) + 1:])
    except ValueError:
        return 0
