"""Local checkpoint/resume (Orbax) for long-running roles.

The reference has no local checkpointing: HF Hub *is* its checkpoint store
(`averaged_model.pt` in the shared repo, hivetrain/averaging_logic.py:481-488,
hivetrain/hf_manager.py:161-173), and a restarted miner loses its optimizer
state by design (training_manager.py:371-377). This module keeps the Hub as
the *protocol* checkpoint (see transport/) and adds what the reference lacks:
a crash-safe local store so a preempted miner resumes mid-round with its
optimizer moments and step counter intact — on TPU, preemption is routine, so
this is a first-class subsystem, not an afterthought.

Design:
- Orbax `CheckpointManager` under the hood (async off: checkpoints here are
  small relative to the push cadence, and synchronous saves keep restart
  semantics trivially correct).
- The unit of persistence is a *composite* pytree: the engine ``TrainState``
  plus the miner's base snapshot and the base revision string, so a resumed
  miner pushes deltas against the same base it was training against.
- Restore is template-driven (like serialization.py): the caller supplies an
  abstract/concrete example tree, so a corrupt or stale checkpoint directory
  fails loudly instead of materializing garbage.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Optional

import jax

logger = logging.getLogger(__name__)

Params = Any


@dataclasses.dataclass
class Snapshot:
    """What a role persists between process lives."""
    state: Any                    # engine TrainState (params, opt_state, step)
    base_params: Params | None    # miner's delta base (None for validator)
    base_revision: str | None     # transport revision the base came from
    lifetime_steps: int | None = None  # monotonic across base pulls (metrics)

    def as_tree(self) -> dict:
        tree = {"state": self.state}
        if self.base_params is not None:
            tree["base_params"] = self.base_params
        return tree


class CheckpointStore:
    """Numbered local checkpoints with retention GC.

    ``save``/``restore`` round-trip a :class:`Snapshot`; the revision string
    travels in Orbax per-step metadata (it is not an array, so it does not
    belong in the pytree).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False,
            ),
        )
        # async lane (save_async): created on first use so sync-only
        # stores never own a thread
        self._async_worker = None

    # -- write --------------------------------------------------------------
    def save(self, step: int, snapshot: Snapshot) -> None:
        ocp = self._ocp
        self._mgr.save(
            int(step),
            args=ocp.args.Composite(
                tree=ocp.args.StandardSave(snapshot.as_tree()),
                meta=ocp.args.JsonSave(
                    {"base_revision": snapshot.base_revision,
                     "lifetime_steps": snapshot.lifetime_steps,
                     # restore must know whether to expect a base subtree
                     # (revision-recoverable bases are not persisted —
                     # MinerLoop._checkpoint_base)
                     "has_base": snapshot.base_params is not None}),
            ),
        )
        # Orbax finalizes each step directory with an atomic rename — a
        # reader (or a restore after a crash mid-save) never sees a torn
        # checkpoint, the same commit discipline as serialization.save_file.
        # wait_until_finished keeps that contract synchronous HERE; the
        # async spelling moves this whole call onto the worker instead.
        self._mgr.wait_until_finished()

    def save_async(self, snapshot: Snapshot, *,
                   precondition=None) -> None:
        """Queue ``save`` on the store's background worker (single-slot
        SUPERSEDE queue, engine/publish.py machinery): a pending save that
        has not started when the next one arrives is dropped — only the
        newest state matters, exactly like delta pushes. The caller must
        hand over an independent snapshot (device copies — the training
        loop's live state gets donated out from under a background reader).

        ``precondition`` runs on the worker immediately before the write
        and aborts the save when it returns False (the miner's non-finite
        screen: the flag's device fetch then happens off-thread). The step
        number is resolved ON the worker via ``next_step()`` — at submit
        time a still-committing predecessor would alias its number. A
        failed save is logged, never raised (same contract as the miner's
        sync path: a failed save must not kill training)."""
        if self._async_worker is None:
            from .engine.publish import PublishWorker
            self._async_worker = PublishWorker(
                name=f"ckpt-save-{os.path.basename(self.directory)}")

        def job():
            if precondition is not None and not precondition():
                return
            self.save(self.next_step(), snapshot)

        self._async_worker.submit(job)

    def flush(self, timeout: float | None = None) -> bool:
        """Drain pending + in-flight async saves (True when drained)."""
        if self._async_worker is None:
            return True
        return self._async_worker.flush(timeout=timeout)

    def next_step(self) -> int:
        """Next free checkpoint key. Keys are a monotonic save sequence, NOT
        the training step — the miner's step counter resets to 0 on every
        base-model pull (protocol semantics), so using it as the key would
        make ``latest_step`` resolve to a stale pre-reset checkpoint and
        collide on re-used step numbers."""
        latest = self._mgr.latest_step()
        return 0 if latest is None else latest + 1

    # -- read ---------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def read_meta(self, step: int | None = None) -> Optional[dict]:
        """The JSON sidecar alone (cheap) — callers shape their restore
        template from it before paying for the tensor restore."""
        ocp = self._ocp
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        restored = self._mgr.restore(
            int(step), args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
        return restored["meta"] or {}

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, template: Snapshot, step: int | None = None
                ) -> Optional[Snapshot]:
        """Restore the latest (or given) checkpoint into the template's
        structure; returns None when the store is empty."""
        ocp = self._ocp
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            template.as_tree())
        restored = self._mgr.restore(
            int(step),
            args=ocp.args.Composite(
                tree=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        tree, meta = restored["tree"], restored["meta"] or {}
        return Snapshot(
            state=tree["state"],
            base_params=tree.get("base_params"),
            base_revision=meta.get("base_revision"),
            lifetime_steps=meta.get("lifetime_steps"),
        )

    def close(self) -> None:
        if self._async_worker is not None:
            # drain first: closing the manager under an in-flight save
            # would turn the newest checkpoint into a logged failure
            self._async_worker.close()
            self._async_worker = None
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
