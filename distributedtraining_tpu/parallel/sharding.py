"""Logical-axis -> mesh-axis resolution for model params and batches.

Models annotate every parameter with logical axis names
(``nn.with_logical_partitioning`` in models/gpt2.py, models/llama.py). This
module resolves those names against a mesh via rules, producing
``NamedSharding``s for params, optimizer state, and batches — the entire
sharding story lives here, the models never mention mesh axes.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Any

# logical axis -> mesh axis (None = replicated). The embed axis maps to fsdp
# so ZeRO-3-style parameter sharding falls out of the same rules; with
# fsdp=1 meshes every spec collapses to replication automatically.
DEFAULT_RULES = (
    ("vocab", "tp"),
    ("qkv", "tp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("embed", "fsdp"),
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
)


def logical_param_specs(model: nn.Module, *, seq_len: int = 8) -> Params:
    """PartitionSpecs-of-logical-names for every param, via shape-only init."""
    import jax.numpy as jnp

    dummy = jnp.zeros((1, seq_len), jnp.int32)
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0), dummy)
    return nn.get_partition_spec(abstract["params"])


def mesh_shardings(model: nn.Module, mesh: Mesh, *, seq_len: int = 8,
                   rules=DEFAULT_RULES) -> Params:
    """NamedShardings for every param on ``mesh`` (feed to jit in/out_shardings
    or device_put)."""
    logical = logical_param_specs(model, seq_len=seq_len)
    return nn.logical_to_mesh_sharding(logical, mesh, rules)


def shard_params(params: Params, shardings: Params) -> Params:
    """Place a (host or differently-sharded) param tree onto the mesh."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings)


def shard_batch_spec(*, seq_sharded: bool = False) -> P:
    """[batch, seq] input sharding: batch over (dp, fsdp); seq over sp when
    ring attention is active."""
    return P(("dp", "fsdp"), "sp" if seq_sharded else None)


def batch_sharding(mesh: Mesh, *, seq_sharded: bool = False) -> NamedSharding:
    return NamedSharding(mesh, shard_batch_spec(seq_sharded=seq_sharded))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def opt_state_shardings(opt_state, param_shardings: Params, mesh: Mesh):
    """Optimizer-state shardings: any leaf shaped like a param inherits that
    param's sharding (adam m/v); scalars replicate.

    Works by matching optax state pytrees whose subtrees mirror the params
    tree (ScaleByAdamState.mu/nu etc.).
    """
    flat_params = {
        tuple(_path_key(p) for p in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(param_shardings)[0]
    }

    def resolve(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return replicated(mesh)
        key = tuple(_path_key(p) for p in path)
        # suffix-match against the params tree: optimizer states embed the
        # params structure under extra prefix levels
        for plen in range(len(key)):
            suffix = key[plen:]
            if suffix in flat_params:
                return flat_params[suffix]
        return replicated(mesh)

    return jax.tree_util.tree_map_with_path(resolve, opt_state)


def _path_key(p):
    return str(getattr(p, "key", getattr(p, "idx", p)))
