"""Device-mesh construction.

Axes (any may be 1 and collapse away):
- dp:   pure data parallel (replicated params, sharded batch)
- fsdp: data parallel with parameter sharding (ZeRO-3-like, free via pjit)
- sp:   sequence/context parallel (ring attention over ICI)
- tp:   tensor parallel (vocab/mlp/heads sharded)

On TPU, ``mesh_utils.create_device_mesh`` lays the mesh out so the innermost
axes ride the fastest ICI links; tp should be innermost, dp outermost
(jax-ml.github.io/scaling-book recipe).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.fsdp, self.sp, self.tp)

    @property
    def n_devices(self) -> int:
        return math.prod(self.shape)


def make_mesh(cfg: MeshConfig | None = None, *, devices=None) -> Mesh:
    """Build a Mesh with axes (dp, fsdp, sp, tp).

    With no config, all visible devices go to dp (the reference-parity
    default: federated outer loop + per-miner data parallel).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if cfg is None:
        cfg = MeshConfig(dp=len(devices))
    if cfg.n_devices > len(devices):
        raise ValueError(
            f"mesh {cfg.shape} needs {cfg.n_devices} devices, have {len(devices)}")
    devices = devices[: cfg.n_devices]
    try:
        dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
    except Exception:
        # CPU virtual devices or odd topologies: plain reshape is fine
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, AXES)


def best_mesh_shape(n_devices: int, *, model_params: int = 0,
                    per_device_memory: int = 16 * 1024**3) -> MeshConfig:
    """Heuristic mesh for N devices: shard params (fsdp) only once the model
    stops fitting replicated; add tp for very large models.

    Rough sizing: Adam training state is ~16 bytes/param fp32
    (p + m + v + grad). tp is capped at 8 so it stays inside one ICI ring.
    """
    if n_devices == 1:
        return MeshConfig()
    state_bytes = model_params * 16
    if model_params and state_bytes > per_device_memory * n_devices // 2:
        tp = min(8, _largest_pow2_divisor(n_devices))
        rest = n_devices // tp
        return MeshConfig(fsdp=rest, tp=tp)
    if model_params and state_bytes > per_device_memory // 2:
        return MeshConfig(fsdp=n_devices)
    return MeshConfig(dp=n_devices)


def resolve_mesh_config(*, n_devices: int, dp: int = 0, fsdp: int = 1,
                        sp: int = 1, tp: int = 1, auto: bool = False,
                        model_params: int = 0,
                        dcn_dp: int = 1) -> MeshConfig:
    """CLI mesh spec -> concrete MeshConfig (pure; role composition calls
    this with the visible device count).

    ``auto=True`` ignores the axis arguments and picks via
    ``best_mesh_shape`` from the model size — dp while the training state
    fits replicated, fsdp/tp as it grows. With ``dcn_dp > 1`` (multi-slice)
    the auto pick is made PER GRANULE and its dp multiplied by ``dcn_dp``,
    so fsdp/sp/tp always fit inside one granule and only dp crosses DCN
    (pod_mesh's hybrid-layout contract). Otherwise dp=0 means "whatever is
    left" after fsdp*sp*tp."""
    if auto:
        if dcn_dp > 1:
            if n_devices % dcn_dp:
                raise ValueError(
                    f"{n_devices} devices not divisible by dcn_dp={dcn_dp}")
            per = best_mesh_shape(n_devices // dcn_dp,
                                  model_params=model_params)
            return dataclasses.replace(per, dp=per.dp * dcn_dp)
        return best_mesh_shape(n_devices, model_params=model_params)
    rest = fsdp * sp * tp
    if dp == 0:
        dp = max(1, n_devices // rest)
    return MeshConfig(dp=dp, fsdp=fsdp, sp=sp, tp=tp)


def _largest_pow2_divisor(n: int) -> int:
    p = 1
    while n % (p * 2) == 0:
        p *= 2
    return p
