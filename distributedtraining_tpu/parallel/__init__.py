"""Mesh + sharding: how the framework scales.

The reference has no intra-node parallelism at all (one torch device per
role, SURVEY.md §2.2). Here scaling is a mesh-configuration change, not a
code change: every engine jits pure step functions whose params/optimizer
shardings come from logical-axis rules resolved against a
``jax.sharding.Mesh`` with axes (dp, fsdp, sp, tp).
"""

from . import multihost
from .mesh import (MeshConfig, make_mesh, best_mesh_shape,
                   resolve_mesh_config)
from .sharding import (
    DEFAULT_RULES,
    logical_param_specs,
    mesh_shardings,
    shard_batch_spec,
    shard_params,
)

__all__ = [
    "multihost",
    "MeshConfig", "make_mesh", "best_mesh_shape", "resolve_mesh_config",
    "DEFAULT_RULES", "logical_param_specs", "mesh_shardings",
    "shard_batch_spec", "shard_params",
]
