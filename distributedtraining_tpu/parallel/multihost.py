"""Multi-host SPMD bring-up (BASELINE.json config 5: v5e-64 pods).

The reference has no multi-node compute plane at all — its "distribution" is
the asynchronous miner/validator/averager outer loop over HF repos
(SURVEY.md §2.2). This module supplies the missing intra-role plane: one
role (say, a miner) spanning a multi-host TPU pod slice as a single SPMD
program, while the outer federated loop stays exactly as it is.

Usage (identical binary on every host of the slice):

    from distributedtraining_tpu.parallel import multihost
    multihost.initialize()               # no-op on single host
    mesh = multihost.pod_mesh(fsdp=8)    # global mesh over all pod chips
    engine = TrainEngine(model, mesh=mesh, ...)

Design notes:
- ``jax.distributed.initialize()`` auto-discovers coordinator/rank on TPU
  pods from the environment; explicit args exist for manual setups.
- Only process 0 should talk to the transports/chain (publish deltas, set
  weights); ``is_coordinator()`` gates that. Data loading uses
  ``process_index`` to shard the document stream.
- Everything degrades to single-host: initialize() is a no-op when JAX sees
  one process, and pod_mesh == make_mesh over local devices.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from .mesh import MeshConfig, make_mesh

logger = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (idempotent, single-host no-op).

    On TPU pods all three arguments auto-discover from the environment; pass
    them explicitly only for manual (e.g. DCN cluster) topologies."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None:
        try:
            n = jax.process_count()
        except Exception:
            n = 1
        if n <= 1:
            # single-process already; nothing to initialize
            _initialized = True
            return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    logger.info("multihost: process %d/%d, %d global devices",
                jax.process_index(), jax.process_count(),
                len(jax.devices()))


def is_coordinator() -> bool:
    """True on the one process that owns transport/chain IO."""
    return jax.process_index() == 0


def pod_mesh(*, dp: int = 0, fsdp: int = 1, sp: int = 1, tp: int = 1):
    """Global mesh over every chip in the pod slice (all processes).

    dp=0 means "whatever is left": dp = n_global_devices / (fsdp*sp*tp).
    The mesh uses jax.devices() (global), so the same jitted step on every
    host forms one SPMD program with XLA collectives riding ICI.
    """
    n = len(jax.devices())
    rest = fsdp * sp * tp
    if dp == 0:
        if n % rest:
            raise ValueError(f"{n} devices not divisible by fsdp*sp*tp={rest}")
        dp = n // rest
    cfg = MeshConfig(dp=dp, fsdp=fsdp, sp=sp, tp=tp)
    if cfg.n_devices != n:
        raise ValueError(f"mesh {cfg} wants {cfg.n_devices} devices, "
                         f"pod has {n}")
    return make_mesh(cfg, devices=jax.devices())


def shard_documents(docs, *, process_index: Optional[int] = None,
                    process_count: Optional[int] = None):
    """Round-robin split of a document stream across processes so each host
    feeds its local batch shard distinct data."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    for i, doc in enumerate(docs):
        if i % pc == pi:
            yield doc
