"""Multi-host SPMD bring-up (BASELINE.json config 5: v5e-64 pods).

The reference has no multi-node compute plane at all — its "distribution" is
the asynchronous miner/validator/averager outer loop over HF repos
(SURVEY.md §2.2). This module supplies the missing intra-role plane: one
role (say, a miner) spanning a multi-host TPU pod slice as a single SPMD
program, while the outer federated loop stays exactly as it is.

Usage (identical binary on every host of the slice):

    from distributedtraining_tpu.parallel import multihost
    multihost.initialize()               # no-op on single host
    mesh = multihost.pod_mesh(fsdp=8)    # global mesh over all pod chips
    engine = TrainEngine(model, mesh=mesh, ...)

Design notes:
- ``jax.distributed.initialize()`` auto-discovers coordinator/rank on TPU
  pods from the environment; explicit args exist for manual setups.
- Only process 0 should talk to the transports/chain (publish deltas, set
  weights); ``is_coordinator()`` gates that. Data loading uses
  ``process_index`` to shard the document stream.
- Everything degrades to single-host: initialize() is a no-op when JAX sees
  one process, and pod_mesh == make_mesh over local devices.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from .mesh import MeshConfig, make_mesh

logger = logging.getLogger(__name__)

_initialized = False

# Environment markers that mean "this process is one of several in a pod/
# cluster job". jax.distributed.initialize() auto-discovers its arguments
# from exactly these launchers; anything else is single-host.
_MULTIPROCESS_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",    # generic jax launcher
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",  # multislice TPU
)


def _gce_tpu_worker_count() -> int:
    """Worker count from the GCE metadata server — plain Cloud TPU pod
    slices launched via gcloud export no env vars; JAX's own cluster
    auto-detect queries this same endpoint. Returns 1 on any failure."""
    if os.environ.get("TPU_SKIP_MDS_QUERY"):
        return 1
    import urllib.request
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "attributes/worker-network-endpoints",
        headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=1.0) as r:
            return len([e for e in r.read().decode().split(",") if e])
    except Exception:  # malformed responses included — never crash startup
        return 1


def _multiprocess_env() -> bool:
    env = os.environ
    if any(env.get(k) for k in _MULTIPROCESS_ENV_VARS):
        return True
    # TPU pod metadata: single-host TPU VMs also set this (one hostname), so
    # it only signals multi-process when several workers are listed
    if len([h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]) > 1:
        return True
    for k in ("SLURM_NTASKS", "SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(env.get(k, "1")) > 1:
                return True
        except ValueError:
            pass
    # last resort, only when this looks like a TPU VM: /dev/accel* (v4 and
    # earlier) or /dev/vfio WITH libtpu importable (v5e+ use vfio, but bare
    # /dev/vfio also exists on non-GCE GPU-passthrough hosts where a
    # metadata.google.internal lookup would stall in DNS — jax's own
    # cloud_tpu detection gates on libtpu the same way). Then ask the
    # metadata server like jax's cloud_tpu_cluster does.
    import glob
    import importlib.util
    if glob.glob("/dev/accel*") or (
            glob.glob("/dev/vfio/*")
            and importlib.util.find_spec("libtpu") is not None):
        return _gce_tpu_worker_count() > 1
    return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (idempotent, single-host no-op).

    On TPU pods all three arguments auto-discover from the environment; pass
    them explicitly only for manual (e.g. DCN cluster) topologies.

    The multi-process decision is made from environment signals alone —
    NEVER by probing jax (``jax.process_count()`` would initialize the XLA
    backend, after which ``jax.distributed.initialize`` unconditionally
    raises "must be called before any JAX calls")."""
    global _initialized
    if _initialized:
        return
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    if not explicit and not _multiprocess_env():
        # single-process launch; nothing to initialize
        _initialized = True
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    logger.info("multihost: process %d/%d, %d global devices",
                jax.process_index(), jax.process_count(),
                len(jax.devices()))


def is_coordinator() -> bool:
    """True on the one process that owns transport/chain IO."""
    return jax.process_index() == 0


def pod_mesh(*, dp: int = 0, fsdp: int = 1, sp: int = 1, tp: int = 1,
             dcn_dp: int = 1):
    """Global mesh over every chip in the pod slice (all processes).

    dp=0 means "whatever is left": dp = n_global_devices / (fsdp*sp*tp).
    The mesh uses jax.devices() (global), so the same jitted step on every
    host forms one SPMD program with XLA collectives riding ICI.

    ``dcn_dp > 1`` declares that the outermost ``dcn_dp`` groups of the dp
    axis cross a slower network (multi-slice DCN, or plain ethernet between
    CPU hosts): the device mesh is laid out so that ONLY that slice of the
    dp axis crosses granule boundaries, keeping fsdp/sp/tp collectives —
    and the intra-granule part of dp — on ICI. Granules are TPU slices when
    the platform exposes ``slice_index``, else processes. dp must be
    divisible by dcn_dp; the fsdp/sp/tp axes must fit inside one granule.
    """
    devs = jax.devices()
    n = len(devs)
    rest = fsdp * sp * tp
    if dp == 0:
        if n % rest:
            raise ValueError(f"{n} devices not divisible by fsdp*sp*tp={rest}")
        dp = n // rest
    cfg = MeshConfig(dp=dp, fsdp=fsdp, sp=sp, tp=tp)
    if cfg.n_devices != n:
        raise ValueError(f"mesh {cfg} wants {cfg.n_devices} devices, "
                         f"pod has {n}")
    if dcn_dp > 1:
        if dp % dcn_dp:
            raise ValueError(f"dp={dp} not divisible by dcn_dp={dcn_dp}")
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        from .mesh import AXES
        inner = (dp // dcn_dp, fsdp, sp, tp)
        outer = (dcn_dp, 1, 1, 1)
        # granule = TPU slice when the platform actually has dcn_dp of
        # them; otherwise processes (CPU hosts report slice_index 0 for
        # every device, so attribute presence alone is not the signal)
        slice_ids = {getattr(d, "slice_index", None) for d in devs}
        use_slices = None not in slice_ids and len(slice_ids) == dcn_dp
        if not use_slices and jax.process_count() == 1:
            # single-process dryrun ONLY (the driver's virtual CPU mesh):
            # no slices and no process granules to split across, so
            # emulate granules as contiguous blocks of the device list —
            # the SAME axis layout the hybrid mesh produces (outer dp
            # slowest-varying), just without real network-distance
            # information. Validates that programs compile+run against
            # the dcn_dp layout without a multi-slice pod. A MULTI-process
            # fleet whose granule count mismatches dcn_dp must still fail
            # loudly below (create_hybrid_device_mesh raises) — silently
            # reshaping there would route "ICI-local" collectives across
            # the slow network.
            # contiguous blocks of the flat list = the granules, which is
            # exactly the row-major layout one reshape produces (the dp
            # axis varies slowest, so its outer dcn_dp groups are the
            # virtual granules)
            import numpy as _np
            dev_array = _np.array(devs).reshape((dp, fsdp, sp, tp))
            return Mesh(dev_array, AXES)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            inner, outer, devices=devs,
            process_is_granule=not use_slices)
        return Mesh(dev_array, AXES)
    return make_mesh(cfg, devices=devs)


def shard_documents(docs, *, process_index: Optional[int] = None,
                    process_count: Optional[int] = None):
    """Round-robin split of a document stream across processes so each host
    feeds its local batch shard distinct data."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    for i, doc in enumerate(docs):
        if i % pc == pi:
            yield doc


# ---------------------------------------------------------------------------
# Coordinator gating: in an SPMD role every process computes, but only one
# may talk to the outside world — N processes each pushing the same delta /
# setting the same weights would hammer the Hub and the chain N-fold.
# ---------------------------------------------------------------------------

def _materialize(tree):
    """Bring a pytree to host-complete values for serialization. FSDP/TP
    leaves sharded across processes are not fully addressable on any single
    host, so this runs a process_allgather — a COLLECTIVE: it must execute
    on every process, which is why the gated publishers call it before the
    coordinator-only branch, never after."""
    import jax as _jax

    leaves = _jax.tree_util.tree_leaves(tree)
    if all(getattr(l, "is_fully_addressable", True) for l in leaves):
        return tree
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(tree, tiled=True)


class CoordinatorGatedTransport:
    """Reads pass through on every process (each host fetches the base for
    itself); writes (publish/gc) run only on the coordinator and silently
    no-op elsewhere. Published trees are materialized host-side first (a
    collective on every process) so cross-process-sharded params serialize."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def publish_delta(self, miner_id, tree, *a, **kw):
        tree = _materialize(tree)
        if not is_coordinator():
            return None
        return self._inner.publish_delta(miner_id, tree, *a, **kw)

    def publish_base(self, tree, *a, **kw):
        tree = _materialize(tree)
        if not is_coordinator():
            # non-coordinators poll base_revision() for the real revision
            return None
        return self._inner.publish_base(tree, *a, **kw)

    def publish_delta_meta(self, miner_id, meta):
        # same one-writer rule as the artifact itself (N processes
        # committing the same rider file would conflict)
        if not is_coordinator():
            return None
        pm = getattr(self._inner, "publish_delta_meta", None)
        return pm(miner_id, meta) if pm is not None else None

    def gc(self, *a, **kw):
        if not is_coordinator():
            return None
        return self._inner.gc(*a, **kw)


class CoordinatorGatedChain:
    """sync/reads pass through; weight emission runs only on the coordinator
    (the reference's one-wallet-per-role model maps to one chain writer per
    SPMD role)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def set_weights(self, *a, **kw):
        if not is_coordinator():
            return None
        return self._inner.set_weights(*a, **kw)


def gate_io(transport, chain):
    """Wrap transport/chain with coordinator gates when running
    multi-process; identity on single host."""
    if jax.process_count() <= 1:
        return transport, chain
    return CoordinatorGatedTransport(transport), CoordinatorGatedChain(chain)
