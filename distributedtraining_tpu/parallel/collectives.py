"""Explicit-collective merge paths (shard_map over ICI).

BASELINE.json config 3 calls for the averager's weighted merge to run as an
ICI all-reduce over pod chips instead of host tensor arithmetic. The pattern:
each device holds a shard of miners' deltas along the stacked miner axis,
computes its local weighted partial sum, and one ``psum`` over the mesh axis
produces the merged model on every device — the classic
partial-sum/all-reduce recipe from the scaling book.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 top-level API, experimental path as fallback
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

Params = Any


def merge_axis(mesh: Mesh) -> str:
    """The mesh axis the averager shards the miner stack over: the largest
    axis (ties prefer dp — the conventional replica axis of an averager
    eval mesh)."""
    order = {"dp": 0, "fsdp": 1, "sp": 2, "tp": 3}
    names = sorted(mesh.shape.keys(), key=lambda n: order.get(n, 9))
    return max(names, key=lambda n: mesh.shape[n])


def stack_deltas_sharded(deltas, mesh: Mesh, axis: str = "dp") -> Params:
    """Stack M deltas into a miner-axis pytree placed with that axis sharded
    over ``axis`` — the ingest path of the ICI merge (BASELINE config 3).

    Leaves are assembled host-side (numpy) and ``device_put`` directly into
    the target sharding, so no single device ever materializes the full
    M x params stack (``delta.stack_deltas`` would). M is padded with
    zero-deltas up to a multiple of the axis size; the padding contributes
    nothing to any weighted merge whose weights are zero-padded to match
    (strategies use ``delta.pad_merge_weights``).
    """
    if not deltas:
        raise ValueError("stack_deltas_sharded: empty sequence")
    import numpy as np
    axis_size = mesh.shape[axis]
    m = len(deltas)
    target = ((m + axis_size - 1) // axis_size) * axis_size

    def stack_leaf(*xs):
        arrs = [np.asarray(x) for x in xs]
        if target > m:
            arrs.extend(np.zeros_like(arrs[0]) for _ in range(target - m))
        stacked = np.stack(arrs, axis=0)
        spec = P(axis, *([None] * arrs[0].ndim))
        return jax.device_put(stacked, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(stack_leaf, *deltas)


def shard_stacked_deltas(stacked: Params, mesh: Mesh, axis: str = "dp") -> Params:
    """Place a [M, ...]-leaved stacked-delta tree with the miner axis sharded
    over ``axis``. M must divide the axis size evenly (pad with zero-deltas
    and zero weights otherwise)."""
    def place(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pad_miner_axis(stacked: Params, weights: jax.Array, multiple: int
                   ) -> tuple[Params, jax.Array]:
    """Pad the miner axis up to a multiple of the mesh axis with zero deltas
    + zero weights so sharding divides evenly; padding contributes nothing.
    ``stacked`` and ``weights`` may already disagree (an ingest-sharded stack
    is pre-padded, the weight vector is not); each is padded independently
    to the common target."""
    m_s = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    m_w = weights.shape[0]
    m = max(m_s, m_w)
    target = ((m + multiple - 1) // multiple) * multiple

    if target > m_s:
        pad = target - m_s

        def pad_leaf(x):
            return jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)

        stacked = jax.tree_util.tree_map(pad_leaf, stacked)
    if target > m_w:
        weights = jnp.concatenate(
            [weights, jnp.zeros((target - m_w,), weights.dtype)])
    return stacked, weights


def psum_weighted_merge(base: Params, stacked: Params, weights: jax.Array,
                        mesh: Mesh, *, axis: str = "dp") -> Params:
    """merged = base + sum_i w_i * delta_i, with the sum over the miner axis
    executed as local partial sums + one ICI all-reduce.

    ``stacked``/``weights`` may live on host or be pre-sharded; they are
    placed with the miner axis over ``axis``. Result is replicated.
    """
    axis_size = mesh.shape[axis]
    stacked, weights = pad_miner_axis(stacked, weights, axis_size)

    in_specs = (
        P(),                                     # base replicated
        jax.tree_util.tree_map(
            lambda x: P(axis, *([None] * (x.ndim - 1))), stacked),
        P(axis),
    )

    def local_merge(b_tree, d_tree, w):
        def leaf(b, d):
            # accumulate (and psum) in the base's dtype so a bf16 wire
            # stack doesn't degrade the reduction — mirrors weighted_merge
            wv = w.reshape((-1,) + (1,) * (d.ndim - 1)).astype(b.dtype)
            partial = jnp.sum(wv * d.astype(b.dtype), axis=0)
            return b + jax.lax.psum(partial, axis)
        return jax.tree_util.tree_map(leaf, b_tree, d_tree)

    fn = _shard_map(local_merge, mesh=mesh, in_specs=in_specs, out_specs=P())
    return fn(base, stacked, weights)
