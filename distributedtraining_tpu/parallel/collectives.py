"""Explicit-collective merge paths (shard_map over ICI).

BASELINE.json config 3 calls for the averager's weighted merge to run as an
ICI all-reduce over pod chips instead of host tensor arithmetic. The pattern:
each device holds a shard of miners' deltas along the stacked miner axis,
computes its local weighted partial sum, and one ``psum`` over the mesh axis
produces the merged model on every device — the classic
partial-sum/all-reduce recipe from the scaling book.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 top-level API, experimental path as fallback
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..utils import devprof, obs

Params = Any

# bucket ladder for the MINER axis of averager merges — the same
# elastic-cohort discipline as engine/batched_eval.BUCKETS: a fleet whose
# accepted-delta count wobbles between 5 and 8 hits ONE compiled merge
# program instead of four (each distinct padded M is a fresh XLA compile
# of the full-tree merge). Beyond the top bucket, multiples of it.
MERGE_BUCKETS = (1, 2, 4, 8, 16)

# (mesh, axis, m_pad) bucket sizes a sharded merge has dispatched (mesh
# None = the single-device stacked path): a NEW entry means a fresh
# compile, recorded in merge.bucket_compiles + the shared compile.ms
# histogram. prefer_compiled consults this to pad a not-yet-compiled
# bucket up to a compiled one (padding waste over a compile storm).
_MERGE_BUCKETS_SEEN: set = set()
# (mesh, axis, treedef, ndims) -> the shard_map weighted-merge callable.
# Built once per mesh/tree-structure and jitted, so every averaging
# round reuses ONE compiled program per bucket — the previous spelling
# rebuilt the shard_map closure per call, which hands XLA a fresh
# function identity and retraces the full merge every round.
_MERGE_PROGRAMS: dict = {}


def reset_merge_cache() -> None:
    """Drop the compiled-program + bucket caches (tests)."""
    _MERGE_BUCKETS_SEEN.clear()
    _MERGE_PROGRAMS.clear()


def merge_bucket(m: int, mesh: Mesh | None = None, axis: str | None = None,
                 *, prefer_compiled: bool = True) -> int:
    """Padded miner-axis size for ``m`` accepted deltas: the smallest
    MERGE_BUCKETS rung >= m (multiples of the top bucket beyond it),
    rounded up to a multiple of the mesh's merge axis so the stack
    shards evenly. With ``prefer_compiled`` (the remediation-era elastic
    discipline), a target whose program is not yet compiled pads up to
    the smallest ALREADY-COMPILED larger bucket instead of walking the
    ladder through fresh compiles."""
    if m < 1:
        raise ValueError(f"merge cohort must hold >= 1 delta, got {m}")
    for b in MERGE_BUCKETS:
        if m <= b:
            target = b
            break
    else:
        big = MERGE_BUCKETS[-1]
        target = ((m + big - 1) // big) * big
    if mesh is not None:
        axis = axis or merge_axis(mesh)
        n = mesh.shape[axis]
        target = ((target + n - 1) // n) * n
    key = (mesh, axis if mesh is not None else None)
    if prefer_compiled and (*key, target) not in _MERGE_BUCKETS_SEEN:
        bigger = sorted(t for (mk, ak, t) in _MERGE_BUCKETS_SEEN
                        if (mk, ak) == key and t >= target)
        if bigger:
            target = bigger[0]
    return target


def merge_axis(mesh: Mesh) -> str:
    """The mesh axis the averager shards the miner stack over: the largest
    axis (ties prefer dp — the conventional replica axis of an averager
    eval mesh)."""
    order = {"dp": 0, "fsdp": 1, "sp": 2, "tp": 3}
    names = sorted(mesh.shape.keys(), key=lambda n: order.get(n, 9))
    return max(names, key=lambda n: mesh.shape[n])


def stack_deltas_sharded(deltas, mesh: Mesh, axis: str = "dp",
                         target: int | None = None) -> Params:
    """Stack M deltas into a miner-axis pytree placed with that axis sharded
    over ``axis`` — the ingest path of the ICI merge (BASELINE config 3).

    Leaves are assembled host-side (numpy) and ``device_put`` directly into
    the target sharding, so no single device ever materializes the full
    M x params stack (``delta.stack_deltas`` would). M is padded with
    zero-deltas up to ``target`` (callers pass ``merge_bucket(...)`` so
    elastic fleets reuse compiled merge programs; default: the next
    multiple of the axis size); the padding contributes nothing to any
    weighted merge whose weights are zero-padded to match (strategies
    use ``delta.pad_merge_weights``).
    """
    if not deltas:
        raise ValueError("stack_deltas_sharded: empty sequence")
    import numpy as np
    axis_size = mesh.shape[axis]
    m = len(deltas)
    target = max(target or 0,
                 ((m + axis_size - 1) // axis_size) * axis_size)
    if target % axis_size:
        raise ValueError(f"stack target {target} does not divide the "
                         f"{axis_size}-wide mesh axis {axis!r}")

    def stack_leaf(*xs):
        arrs = [np.asarray(x) for x in xs]
        if target > m:
            arrs.extend(np.zeros_like(arrs[0]) for _ in range(target - m))
        stacked = np.stack(arrs, axis=0)
        spec = P(axis, *([None] * arrs[0].ndim))
        return jax.device_put(stacked, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(stack_leaf, *deltas)


def shard_stacked_deltas(stacked: Params, mesh: Mesh, axis: str = "dp") -> Params:
    """Place a [M, ...]-leaved stacked-delta tree with the miner axis sharded
    over ``axis``. M must divide the axis size evenly (pad with zero-deltas
    and zero weights otherwise)."""
    def place(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pad_miner_axis(stacked: Params, weights: jax.Array, multiple: int
                   ) -> tuple[Params, jax.Array]:
    """Pad the miner axis up to a multiple of the mesh axis with zero deltas
    + zero weights so sharding divides evenly; padding contributes nothing.
    ``stacked`` and ``weights`` may already disagree (an ingest-sharded stack
    is pre-padded, the weight vector is not); each is padded independently
    to the common target."""
    m_s = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    m_w = weights.shape[0]
    m = max(m_s, m_w)
    target = ((m + multiple - 1) // multiple) * multiple

    if target > m_s:
        pad = target - m_s

        def pad_leaf(x):
            return jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)

        stacked = jax.tree_util.tree_map(pad_leaf, stacked)
    if target > m_w:
        weights = jnp.concatenate(
            [weights, jnp.zeros((target - m_w,), weights.dtype)])
    return stacked, weights


def psum_weighted_merge(base: Params, stacked: Params, weights: jax.Array,
                        mesh: Mesh, *, axis: str = "dp") -> Params:
    """merged = base + sum_i w_i * delta_i, with the sum over the miner axis
    executed as local partial sums + one ICI all-reduce.

    ``stacked``/``weights`` may live on host or be pre-sharded; they are
    placed with the miner axis over ``axis``. Result is replicated.
    """
    axis_size = mesh.shape[axis]
    stacked, weights = pad_miner_axis(stacked, weights, axis_size)

    in_specs = (
        P(),                                     # base replicated
        jax.tree_util.tree_map(
            lambda x: P(axis, *([None] * (x.ndim - 1))), stacked),
        P(axis),
    )

    def local_merge(b_tree, d_tree, w):
        def leaf(b, d):
            # accumulate (and psum) in the base's dtype so a bf16 wire
            # stack doesn't degrade the reduction — mirrors weighted_merge
            wv = w.reshape((-1,) + (1,) * (d.ndim - 1)).astype(b.dtype)
            partial = jnp.sum(wv * d.astype(b.dtype), axis=0)
            return b + jax.lax.psum(partial, axis)
        return jax.tree_util.tree_map(leaf, b_tree, d_tree)

    fn = _shard_map(local_merge, mesh=mesh, in_specs=in_specs, out_specs=P())
    return fn(base, stacked, weights)


def sharded_cohort_merge(base: Params, stacked: Params, weights,
                         mesh: Mesh, *, axis: str | None = None) -> Params:
    """The production spelling of :func:`psum_weighted_merge`: identical
    math (local partial sums over the sharded miner axis + one ICI
    all-reduce), but the shard_map program is built ONCE per
    (mesh, axis, tree structure), jitted, and dispatched against
    bucket-padded stacks — so a pod merges a whole cohort in one fused,
    CACHED program round after round. psum_weighted_merge rebuilt its
    closure per call (a fresh trace every averaging round), and padded
    to the raw axis multiple (a fresh compile every time the accepted
    count wobbled); this path pads to ``merge_bucket`` and records fresh
    buckets in merge.bucket_compiles + the shared compile.ms histogram.
    """
    axis = axis or merge_axis(mesh)
    m_s = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    m_w = weights.shape[0]
    m_pad = merge_bucket(max(m_s, m_w), mesh, axis)
    stacked, weights = pad_miner_axis(stacked, weights, m_pad)

    treedef = jax.tree_util.tree_structure(stacked)
    ndims = tuple(l.ndim for l in jax.tree_util.tree_leaves(stacked))
    pkey = (mesh, axis, treedef, ndims)
    program = _MERGE_PROGRAMS.get(pkey)
    if program is None:
        in_specs = (
            P(),
            jax.tree_util.tree_unflatten(
                treedef, [P(axis, *([None] * (nd - 1))) for nd in ndims]),
            P(axis),
        )

        def local_merge(b_tree, d_tree, w):
            def leaf(b, d):
                wv = w.reshape((-1,) + (1,) * (d.ndim - 1)).astype(b.dtype)
                partial = jnp.sum(wv * d.astype(b.dtype), axis=0)
                return b + jax.lax.psum(partial, axis)
            return jax.tree_util.tree_map(leaf, b_tree, d_tree)

        program = devprof.wrap(
            "merge.sharded",
            jax.jit(_shard_map(local_merge, mesh=mesh,
                               in_specs=in_specs, out_specs=P())),
            # (base, stacked, weights) -> padded miner-axis size, the
            # bucket the executable cache keys this merge variant on
            bucket=lambda a, kw: jax.tree_util.tree_leaves(
                a[1])[0].shape[0])
        _MERGE_PROGRAMS[pkey] = program

    bkey = (mesh, axis, m_pad)
    if bkey not in _MERGE_BUCKETS_SEEN:
        _MERGE_BUCKETS_SEEN.add(bkey)
        obs.count("merge.bucket_compiles")
        t0 = time.perf_counter()
        out = program(base, stacked, weights)
        # first-dispatch wall time = trace + compile (+ async dispatch),
        # the same accounting as batched_eval._timed_compile
        obs.observe("compile.ms", (time.perf_counter() - t0) * 1e3)
        return out
    return program(base, stacked, weights)


def mark_merge_bucket(m_pad: int, mesh: Mesh | None = None,
                      axis: str | None = None) -> bool:
    """Record a single-device (mesh=None) merge bucket as compiled;
    returns True when it was fresh. The stacked single-device strategies
    (ParameterizedMerge/GeneticMerge) key their own program caches on
    m_pad — this shared ledger is what lets merge_bucket's
    prefer_compiled avoid walking them through fresh compiles too."""
    key = (mesh, axis if mesh is not None else None, m_pad)
    if key in _MERGE_BUCKETS_SEEN:
        return False
    _MERGE_BUCKETS_SEEN.add(key)
    obs.count("merge.bucket_compiles")
    return True
