"""Pytree weight-delta algebra.

The smallest, most-depended-on layer of the framework: a *delta* is the
per-parameter difference ``trained - base`` between two structurally identical
parameter pytrees. Miners ship deltas, validators apply them for scoring, the
averager merges stacks of them.

Reference behavior being reproduced (TPU-idiomatically):
- delta computation: hivetrain/training_manager.py:417-422
- delta application: hivetrain/validation_logic.py:251-259
- NaN screening of untrusted submissions: hivetrain/averaging_logic.py:121-127
- shape screening of untrusted submissions: hivetrain/averaging_logic.py:404-410

Everything here is a pure function on pytrees; the heavy ones are jittable.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .utils import devprof, obs

Params = Any  # a pytree of arrays


def tree_sub(a: Params, b: Params) -> Params:
    """Elementwise ``a - b`` over structurally identical pytrees."""
    return jax.tree_util.tree_map(lambda x, y: x - y, a, b)


def tree_add(a: Params, b: Params) -> Params:
    """Elementwise ``a + b`` over structurally identical pytrees."""
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def compute_delta(trained: Params, base: Params,
                  wire_dtype: str | None = None) -> Params:
    """delta = trained - base (the artifact a miner uploads).

    ``wire_dtype="bfloat16"`` casts the result for the wire: half the
    artifact bytes, transport bandwidth, and merge HBM. The precision
    cost is bf16 rounding of the DELTA (not the weights) — ~0.4% relative
    on an update the averager then mixes at f32 (weighted_merge upcasts).
    A documented extension over the reference, which ships f32 torch
    tensors (training_manager.py:417-422); receivers accept both
    spellings (screen_delta ``extra_dtypes``), so publishers opt in
    per-miner without a fleet-wide flag."""
    d = tree_sub(trained, base)
    if wire_dtype is None:
        return d
    dt = jnp.dtype(wire_dtype)
    return jax.tree_util.tree_map(
        lambda x: x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, d)


def apply_delta(base: Params, delta: Params) -> Params:
    """Reconstruct trained params from base + delta."""
    return tree_add(base, delta)


def tree_scale(a: Params, s) -> Params:
    return jax.tree_util.tree_map(lambda x: x * s, a)


def zeros_like(a: Params) -> Params:
    return jax.tree_util.tree_map(jnp.zeros_like, a)


# ---------------------------------------------------------------------------
# Screening of untrusted submissions
# ---------------------------------------------------------------------------

def tree_finite(tree: Params) -> jax.Array:
    """Scalar bool array: True when EVERY leaf is finite. The jittable
    body of the finiteness screen — publishers fuse it into their jitted
    snapshot programs (MinerLoop's delta+wire+compress program returns the
    delta AND this flag from ONE program), so the screen costs no separate
    dispatch or host round-trip on the push path. Float leaves only are
    screened; integer leaves are finite by construction."""
    flags = [jnp.any(~jnp.isfinite(leaf))
             for leaf in jax.tree_util.tree_leaves(tree)
             if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    if not flags:
        return jnp.asarray(True)
    return jnp.logical_not(jnp.any(jnp.stack(flags)))


_tree_finite_jit = devprof.wrap("delta.finite", jax.jit(tree_finite))


def has_nonfinite(tree: Params) -> bool:
    """True if any leaf contains NaN/Inf. Host-side screen for untrusted
    deltas. One jitted program, NOT an eager per-leaf loop: on a
    cross-process mesh each eager op is its own collective program, and a
    ~150-leaf model would issue ~150 gloo/ICI round-trips per screen."""
    if not jax.tree_util.tree_leaves(tree):
        return False
    return not bool(jax.device_get(_tree_finite_jit(tree)))


def shapes_match(tree: Params, reference: Params, *, check_dtype: bool = False,
                 extra_dtypes: Sequence[str] = ()) -> bool:
    """True iff ``tree`` has the same structure and per-leaf shapes as ``reference``.

    Used to reject malformed miner submissions before any compute touches
    them. ``extra_dtypes`` lists alternate dtypes a FLOAT leaf may carry in
    addition to the reference's own (the bf16 wire-delta spelling) — f64 or
    integer substitutions stay rejected.
    """
    ts = jax.tree_util.tree_structure(tree)
    rs = jax.tree_util.tree_structure(reference)
    if ts != rs:
        return False
    extra = {np.dtype(d) for d in extra_dtypes}
    for a, b in zip(jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(reference)):
        if tuple(np.shape(a)) != tuple(np.shape(b)):
            return False
        if check_dtype:
            # numpy-side comparison: jnp.asarray would silently downcast a
            # hostile f64 wire tensor to f32 under x64-disabled JAX and the
            # check would pass vacuously.
            da = a.dtype if hasattr(a, "dtype") else np.asarray(a).dtype
            db = b.dtype if hasattr(b, "dtype") else np.asarray(b).dtype
            if np.dtype(da) != np.dtype(db) and not (
                    np.dtype(da) in extra
                    and np.issubdtype(np.dtype(db), np.floating)):
                return False
    return True


def screen_delta(delta: Params, base: Params, *, max_abs: float | None = None,
                 check_dtype: bool = True,
                 extra_dtypes: Sequence[str] = ("bfloat16",)
                 ) -> tuple[bool, str]:
    """Full admission screen for an untrusted delta.

    Returns (ok, reason). Checks structure/shape/dtype parity with the base,
    finiteness, and an optional magnitude cap (a crude poisoning guard the
    reference lacks). dtype parity matters: a f64/i64 submission would
    silently promote the merge and double its memory. bf16 is accepted by
    default wherever the base leaf is floating (the half-bytes wire
    spelling of compute_delta(wire_dtype=...) — it cannot promote or grow
    anything).
    """
    if not shapes_match(delta, base, check_dtype=check_dtype,
                        extra_dtypes=extra_dtypes):
        return False, "shape_mismatch"
    if has_nonfinite(delta):
        return False, "nonfinite"
    # <= 0 disables, exactly like None: this is THE home of that rule so
    # callers wiring a config value through never reinvent (or forget)
    # the translation — max_abs=0 rejecting everything would zero a whole
    # subnet's scores
    if max_abs is not None and max_abs > 0:
        m = global_max_abs(delta)
        if m > max_abs:
            return False, f"magnitude_exceeded({m:.3e}>{max_abs:.3e})"
    return True, "ok"


def _cohort_screen_stats(*deltas: Params) -> tuple[jax.Array, jax.Array]:
    """Per-tree (finite flag, max |value|) for a cohort of structurally
    identical deltas — the jittable body of the batched admission screen.
    ONE program computes what the serial path dispatches as two programs
    PER MINER (``has_nonfinite`` + ``global_max_abs``), so screening cost
    stays ~flat in cohort size. Returns ([K] bool, [K] f32)."""
    fins, maxs = [], []
    for d in deltas:
        leaves = jax.tree_util.tree_leaves(d)
        flags = [jnp.any(~jnp.isfinite(l)) for l in leaves
                 if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
        fins.append(jnp.logical_not(jnp.any(jnp.stack(flags)))
                    if flags else jnp.asarray(True))
        maxs.append(jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
            if leaves else jnp.asarray(0.0, jnp.float32))
    return jnp.stack(fins), jnp.stack(maxs)


_cohort_screen_stats_jit = devprof.wrap(
    "delta.screen", jax.jit(_cohort_screen_stats),
    bucket=lambda a, kw: len(a))  # screen arity (bucket-padded chunk)

# device memory per screen dispatch is bounded at SCREEN_CHUNK x params
# (the chunked_weighted_merge discipline — an averager may gather ~100
# full deltas and must not stage them all on one chip at once); arity is
# bucket-padded (repeat, not zero-alloc) so recompiles are bounded too
SCREEN_CHUNK = 8
_SCREEN_BUCKETS = (1, 2, 4, 8)


def _screen_arity(k: int) -> int:
    for b in _SCREEN_BUCKETS:
        if k <= b:
            return b
    return SCREEN_CHUNK


# (arity, leaf shape/dtype signature) combinations already dispatched —
# a NEW one means jit traces + compiles a fresh screen program, whose
# cost is recorded in the shared ``compile.ms`` histogram (the
# compile-time accounting the recompile counters alone don't give)
_SCREEN_COMPILED: set = set()


def screen_deltas(deltas: Sequence[Params], base: Params, *,
                  max_abs: float | None = None, check_dtype: bool = True,
                  extra_dtypes: Sequence[str] = ("bfloat16",),
                  chunk: int = SCREEN_CHUNK) -> list[tuple[bool, str]]:
    """Batched ``screen_delta``: identical per-delta verdicts (same
    reasons, same check order — shape, finiteness, magnitude), with the
    finite/max-abs device work fused into one jitted program per chunk of
    ``chunk`` deltas instead of two dispatches per miner.

    Shape/dtype parity is checked host-side per delta first (pure
    metadata); survivors are grouped by leaf-dtype signature (a mixed
    f32/bf16-wire fleet must not stack into one promoted program) and
    screened ``chunk`` at a time. Short chunks are arity-padded by
    REPEATING a member (no zero-tree allocation) up to a small bucket
    ladder so a wobbling cohort size hits cached compiles.

    v2 PACKED deltas (is_packed_v2) screen in their packed form — no
    densify: admission is ``packed_matches`` (the field-wise analogue of
    the shape check), then a fused ``_packed_screen_stats`` program per
    chunk whose finite/max verdicts equal the dense screen's on the
    densified tree. Packed entries group by their full leaf
    shape/dtype signature (k varies per publisher, so shapes do too).
    """
    results: list[tuple[bool, str] | None] = [None] * len(deltas)
    by_sig: dict[tuple, list[int]] = {}
    packed_by_sig: dict[tuple, list[int]] = {}
    for i, d in enumerate(deltas):
        if is_packed_v2(d):
            if not packed_matches(d, base):
                results[i] = (False, "shape_mismatch")
                continue
            sig = ("packed",) + tuple(
                (tuple(np.shape(l)), str(np.asarray(l).dtype))
                for l in jax.tree_util.tree_leaves(d["leaves"]))
            packed_by_sig.setdefault(sig, []).append(i)
            continue
        if not shapes_match(d, base, check_dtype=check_dtype,
                            extra_dtypes=extra_dtypes):
            results[i] = (False, "shape_mismatch")
            continue
        sig = tuple(str(np.asarray(l).dtype)
                    for l in jax.tree_util.tree_leaves(d))
        by_sig.setdefault(sig, []).append(i)
    cap = max_abs is not None and max_abs > 0

    def run_chunks(idx_groups, stats_fn, tree_of):
        for idxs in idx_groups:
            for c in range(0, len(idxs), max(1, chunk)):
                part = idxs[c:c + max(1, chunk)]
                arity = _screen_arity(len(part))
                args = [tree_of(deltas[i]) for i in part]
                args += [args[0]] * (arity - len(args))
                ckey = (stats_fn is _packed_screen_stats_jit, arity, tuple(
                    (tuple(np.asarray(l).shape), str(np.asarray(l).dtype))
                    for l in jax.tree_util.tree_leaves(args[0])))
                fresh = ckey not in _SCREEN_COMPILED
                if fresh:
                    _SCREEN_COMPILED.add(ckey)
                    obs.count("screen.fresh_compiles")
                    t0 = time.perf_counter()
                stats = stats_fn(*args)
                if fresh:
                    # first-dispatch wall time: trace + compile (+ the
                    # async dispatch); the fused program's execution
                    # overlaps
                    obs.observe("compile.ms",
                                (time.perf_counter() - t0) * 1e3)
                finite, mags = jax.device_get(stats)
                for slot, i in enumerate(part):
                    if not bool(finite[slot]):
                        results[i] = (False, "nonfinite")
                    elif cap and float(mags[slot]) > max_abs:
                        results[i] = (
                            False,
                            f"magnitude_exceeded({float(mags[slot]):.3e}"
                            f">{max_abs:.3e})")
                    else:
                        results[i] = (True, "ok")

    run_chunks(by_sig.values(), _cohort_screen_stats_jit, lambda d: d)
    run_chunks(packed_by_sig.values(), _packed_screen_stats_jit,
               lambda d: d["leaves"])
    return results  # type: ignore[return-value]


def global_max_abs(tree: Params) -> float:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return 0.0
    return float(jax.device_get(jnp.max(jnp.stack([jnp.max(jnp.abs(l)) for l in leaves]))))


def global_norm(tree: Params) -> float:
    """L2 norm over all leaves (delta-magnitude diagnostic)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return 0.0
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return float(jax.device_get(jnp.sqrt(sq)))


# ---------------------------------------------------------------------------
# int8 wire quantization (an opt-in WIRE format, like the bf16 cast above
# but 4x: per-tensor symmetric scales, error feedback at the publisher)
# ---------------------------------------------------------------------------

def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and set(node) == {"q", "scale"}


def quantize_delta(delta: Params) -> Params:
    """Float delta -> int8 wire tree: every leaf becomes
    ``{"q": int8, "scale": f32 scalar}`` (symmetric, scale = max|x|/127).

    A wire format only: receivers dequantize at ingest
    (``dequantize_delta``) and everything downstream — screens, apply,
    merge — runs on the float tree, so the scale being attacker-controlled
    adds nothing the magnitude/finiteness screens don't already catch.
    Per-artifact rounding error is bounded by one step (max|x|/127 per
    tensor); NOTE this protocol's artifacts REPLACE each other (each push
    re-publishes the whole cumulative delta), so error-feedback-style
    residual carrying would ADD error here, not cancel it — don't.
    All-float trees only (matching quantized_template), enforced loudly.
    Jittable."""
    def leaf(x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            raise ValueError(
                "quantize_delta: non-float leaf of dtype "
                f"{jnp.asarray(x).dtype} — the int8 wire format covers "
                "all-float delta trees only")
        scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / 127.0
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}
    return jax.tree_util.tree_map(leaf, delta)


def dequantize_delta(qtree: Params) -> Params:
    """Inverse of quantize_delta (f32 out). Jittable."""
    return jax.tree_util.tree_map(
        lambda d: d["q"].astype(jnp.float32) * d["scale"],
        qtree, is_leaf=_is_qleaf)


def quantized_template(base_template: Params) -> Params:
    """Host-side zeros tree in the int8 wire structure — the
    template-restoring load's discriminator for quantized submissions
    (engine/lora_train.py fetch_delta_any's try-chain)."""
    return jax.tree_util.tree_map(
        lambda x: {"q": np.zeros(np.shape(x), np.int8),
                   "scale": np.zeros((), np.float32)},
        base_template)


# ---------------------------------------------------------------------------
# Stacking: the averager's miner axis
# ---------------------------------------------------------------------------

def stack_deltas(deltas: Sequence[Params]) -> Params:
    """Stack M structurally identical deltas into one pytree with a leading
    miner axis on every leaf: leaf shape (s0, ...) -> (M, s0, ...).

    This is the TPU-native answer to the reference's per-batch disk reload of
    every cached delta (hivetrain/averaging_logic.py:450-470): one stacked
    pytree makes the merge a single einsum-like jitted computation and lets the
    miner axis be sharded across devices.
    """
    if not deltas:
        raise ValueError("stack_deltas: empty sequence")
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *deltas)


def miner_axis_size(stacked: Params) -> int:
    """Leading-axis length of a stacked-delta tree (may exceed the real miner
    count when the stack was zero-padded for even sharding)."""
    return jax.tree_util.tree_leaves(stacked)[0].shape[0]


def pad_merge_weights(weights: jax.Array, m_padded: int) -> jax.Array:
    """Zero-pad a (M,) mixing vector to a zero-padded stack's leading size:
    padding slots weigh nothing, so the merge is unchanged. Normalize
    (softmax etc.) over the REAL M before padding — normalizing after would
    leak probability mass onto the zero deltas and shrink the update."""
    m = weights.shape[0]
    if m == m_padded:
        return weights
    if m > m_padded:
        raise ValueError(f"{m} weights for a {m_padded}-entry stack")
    return jnp.concatenate(
        [weights, jnp.zeros((m_padded - m,), weights.dtype)])


def pad_stack(stacked: Params, k_pad: int) -> Params:
    """Zero-pad a stacked-delta tree's leading (miner/candidate) axis up to
    ``k_pad``. Padded slots are zero deltas: applied to a base they
    reproduce the base exactly, so a batched evaluator's padded candidates
    cost compute but never perturb real slots (the bucket-padding
    discipline of engine/batched_eval.py, mirroring pad_merge_weights for
    merges). Jittable for any fixed k_pad."""
    k = miner_axis_size(stacked)
    if k == k_pad:
        return stacked
    if k > k_pad:
        raise ValueError(f"cannot pad a {k}-entry stack down to {k_pad}")

    def pad_leaf(x):
        return jnp.concatenate(
            [x, jnp.zeros((k_pad - k,) + x.shape[1:], x.dtype)], axis=0)

    return jax.tree_util.tree_map(pad_leaf, stacked)


def combine_candidate_deltas(stacked: Params, weight_matrix: jax.Array
                             ) -> Params:
    """[P, M] mixing matrix x [M, ...]-stacked deltas -> [P, ...]-stacked
    CANDIDATE deltas: candidate p's delta is ``sum_i W[p, i] * delta_i``.

    This is how a population of merge-weight vectors (GeneticMerge) becomes
    one cohort for the batched evaluator: every row is the delta of one
    candidate mixture, and ``base + candidate_delta[p]`` equals
    ``weighted_merge(base, stacked, W[p])`` exactly (same contraction, f32
    accumulation against a f32 base happens at apply time). Jittable;
    materializes P x params, so single-device use only at small P."""
    def leaf(d):
        # contract in f32 and KEEP f32: rounding the combined delta back to
        # a bf16 wire stack's dtype would perturb the candidate relative to
        # weighted_merge's f32-accumulated result
        w = weight_matrix.astype(jnp.float32)
        return jnp.einsum("pm,m...->p...", w, d.astype(jnp.float32))

    return jax.tree_util.tree_map(leaf, stacked)


def unstack_deltas(stacked: Params) -> list[Params]:
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def weighted_merge(base: Params, stacked_deltas: Params, weights: jax.Array) -> Params:
    """merged = base + sum_i softmax-free weights[i] * delta_i.

    ``weights`` has shape (M,). Jittable; differentiable w.r.t. ``weights``,
    which is how the parameterized averager gets its meta-gradient for free
    (replacing the manual inner-product formula at
    hivetrain/averaging_logic.py:513-528).
    """
    def merge_leaf(b, d):
        # accumulate in the BASE's dtype (f32 for f32 params): a bf16 wire
        # stack must not drag the weighted sum down to bf16. The upcast
        # fuses into the multiply — no extra materialization.
        w = weights.reshape((-1,) + (1,) * (d.ndim - 1)).astype(b.dtype)
        return b + jnp.sum(w * d.astype(b.dtype), axis=0)

    return jax.tree_util.tree_map(merge_leaf, base, stacked_deltas)


# jitted once at module level: per-call jax.jit(weighted_merge) creates a
# fresh function identity each time and retraces/recompiles every round
weighted_merge_jit = devprof.wrap(
    "delta.merge", jax.jit(weighted_merge),
    # (base, stacked, weights) -> miner-axis size, the compiled variant
    # the executable cache keys this merge on
    bucket=lambda a, kw: jax.tree_util.tree_leaves(a[1])[0].shape[0])


def weighted_merge_flat(base: Params, stacked_deltas: Params,
                        weights: jax.Array) -> Params:
    """``weighted_merge`` computed over one raveled buffer instead of
    leaf-by-leaf.

    A GPT-2-124M tree has ~150 leaves; merging per leaf dispatches ~150
    small bandwidth-bound kernels whose edge/launch overheads cap the merge
    well under HBM peak (measured 292 GB/s on v5e, docs/perf.md). Raveling
    turns the whole merge into ONE [M] x [M, N] contraction plus an [N]
    add — a single kernel XLA tiles at near peak — and the unravel back to
    the tree is slice+reshape views fused into the same program. Same
    result, same differentiability w.r.t. ``weights``.

    Transient-memory cost: the ``jnp.concatenate`` materializes a second
    full [M, N] buffer (plus the f32 upcast of each row), roughly DOUBLING
    peak HBM during the merge versus the leafwise spelling. Fine at the
    124M bench scale it serves; do not promote it into the averager for
    7B/8B full-delta merges without a per-leaf-group variant.
    """
    from jax.flatten_util import ravel_pytree

    base_flat, unravel = ravel_pytree(base)
    # ravel each miner's delta row with the same leaf order as the base
    leaves = jax.tree_util.tree_leaves(stacked_deltas)
    m = leaves[0].shape[0]
    stacked_flat = jnp.concatenate(
        [l.reshape(m, -1).astype(base_flat.dtype) for l in leaves], axis=1)
    merged_flat = base_flat + jnp.einsum(
        "m,mn->n", weights.astype(base_flat.dtype), stacked_flat)
    return unravel(merged_flat)


def chunked_weighted_merge(base: Params, deltas: Sequence[Params],
                           weights: jax.Array, *, chunk: int = 8) -> Params:
    """``weighted_merge`` over a HOST-side delta list with bounded device
    memory: at most ``chunk`` deltas are stacked on-device at a time.

    Why it exists: the reference merges up to a whole subnet's submissions
    (100 uids) by re-reading each from disk per batch
    (averaging_logic.py:450-470) — unbounded M, terrible bandwidth. The
    stacked merge is the fast spelling but materializes M x params on one
    device: ~90 full GPT-2-124M deltas is ~45 GB, past any single chip's
    HBM. This path accumulates chunk partial sums instead —
    O(chunk x params) device memory, one compiled program for every chunk
    (the last one is zero-padded to the same shape), identical math.
    The mesh averager doesn't need it (the miner axis is ingest-sharded
    across devices, parallel/collectives.py).
    """
    m = len(deltas)
    if m == 0:
        raise ValueError("chunked_weighted_merge: empty delta list")
    if weights.shape[0] != m:
        raise ValueError(f"{weights.shape[0]} weights for {m} deltas")
    chunk = max(1, min(chunk, m))
    # the accumulator step IS weighted_merge (acc + sum w_i d_i), reused
    # through the module-level jitted spelling so repeated averaging
    # rounds hit the same compiled program instead of retracing
    merged = base
    zero = None
    for i in range(0, m, chunk):
        part = list(deltas[i:i + chunk])
        if len(part) < chunk:
            # pad with zero deltas so every chunk compiles to ONE program
            if zero is None:
                zero = zeros_like(part[0])
            part = part + [zero] * (chunk - len(part))
        merged = weighted_merge_jit(merged, stack_deltas(part),
                                    pad_merge_weights(weights[i:i + chunk],
                                                      chunk))
    return merged


def per_tensor_weighted_merge(base: Params, stacked_deltas: Params, weights: Params) -> Params:
    """Merge with per-miner *and* per-tensor mixing weights.

    ``weights`` is a pytree matching ``base``'s structure whose leaves have
    shape (M,) — one mixing vector per parameter tensor. This is the
    production merge of the reference (ParameterizedAverager,
    hivetrain/averaging_logic.py:422-448, where ``self.weights`` is
    (num_models, num_params)).
    """
    def merge_leaf(b, d, w):
        wv = w.reshape((-1,) + (1,) * (d.ndim - 1)).astype(b.dtype)
        return b + jnp.sum(wv * d.astype(b.dtype), axis=0)

    return jax.tree_util.tree_map(merge_leaf, base, stacked_deltas, weights)


def init_merge_weights(base: Params, num_miners: int, *, per_tensor: bool = True,
                       value: float | None = None) -> Params | jax.Array:
    """Uniform initial mixing weights (1/M each, like the reference's
    torch.ones/num_models at hivetrain/averaging_logic.py:363)."""
    v = (1.0 / num_miners) if value is None else value
    if not per_tensor:
        return jnp.full((num_miners,), v, dtype=jnp.float32)
    return jax.tree_util.tree_map(
        lambda _: jnp.full((num_miners,), v, dtype=jnp.float32), base
    )


def normalized_merge_weights(miner_ids: Sequence[str],
                             consensus: dict[str, float] | None
                             ) -> jax.Array:
    """Consensus scores -> normalized (M,) mixing vector — THE home of
    the consensus→weights rule so every merge path normalizes the same
    way: negative scores clamp to zero, an all-zero (or absent) score
    set falls back to uniform, and normalization ALWAYS runs over the
    REAL, unpadded miner count. Padding to a mesh axis or a compile
    bucket happens AFTER, via :func:`pad_merge_weights`, whose padded
    slots weigh nothing — normalizing by a padded m would shrink every
    real miner's weight by the padding ratio (a 1-miner cohort padded to
    an 8-wide mesh axis would publish 1/8th of the update)."""
    m = len(miner_ids)
    if m == 0:
        raise ValueError("normalized_merge_weights: empty cohort")
    if not consensus:
        return jnp.full((m,), 1.0 / m, jnp.float32)
    raw = np.asarray([max(float(consensus.get(h, 0.0)), 0.0)
                      for h in miner_ids], np.float32)
    total = float(raw.sum())
    if not np.isfinite(total) or total <= 0:
        return jnp.full((m,), 1.0 / m, jnp.float32)
    return jnp.asarray(raw / total)


# ---------------------------------------------------------------------------
# top-k sparse wire compression (the >=8x-beyond-int8 format for the 7B/8B
# configs: 1.42 GB f32 at 355M, ~8 GB/push/miner at 8B — sparse8 at the
# default density ships the same push in ~2% of the f32 bytes)
# ---------------------------------------------------------------------------

# Self-describing wire format "sparse8": a msgpack dict
#   {"__delta_format__": 1, "leaves": {<state-dict path>: 
#       {"idx": int32[k], "q": int8[k], "scale": f32 scalar}}}
# per-leaf top-k by |value| with the kept values int8-quantized. Unlike
# the dense int8 tree it is NOT template-discriminable (k varies with the
# publisher's density flag), so receivers detect it by the format marker
# and validate it field-by-field against the BASE template
# (sparse_delta_from_bytes) — bounds-checked indices, pinned dtypes,
# capped k. Like every wire format here: NO error feedback — pushes
# REPLACE each other (each one re-publishes the whole cumulative delta),
# so carrying a residual into the next push would add the superseded
# push's rounding error (see MinerLoop._push_delta).

SPARSE_FORMAT_KEY = "__delta_format__"
SPARSE_FORMAT_TOPK8 = 1
# leaves at or below this size ship dense (k = n): biases and layernorm
# scales are a rounding error of the artifact bytes but carry outsized
# loss impact, so sparsifying them buys nothing and costs trajectory
SPARSE_DENSE_CUTOFF = 4096


def sparse_k(n: int, density: float) -> int:
    """Per-leaf kept-coordinate count: dense below the cutoff, else
    ceil(n * density) — at LEAST the density fraction, never 0."""
    if n <= SPARSE_DENSE_CUTOFF:
        return n
    return max(1, -int(-n * density // 1))


def sparsify_delta(delta: Params, *, density: float = 1.0 / 64.0) -> Params:
    """Float delta -> sparse8 wire tree (jittable; k is static per leaf).

    Keeps the k largest-|value| coordinates per tensor, int8-quantized
    against that tensor's own max (scale = max|kept|/127). density=1/64
    is ~51x smaller than f32 / ~13x smaller than the dense int8 wire at
    124M (5 bytes per kept coordinate: int32 idx + int8 q)."""
    if not 0.0 < density <= 1.0:
        raise ValueError(f"density must be in (0, 1], got {density}")

    def leaf(x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            raise ValueError(
                "sparsify_delta: non-float leaf of dtype "
                f"{jnp.asarray(x).dtype} — sparse8 covers all-float "
                "delta trees only")
        flat = jnp.asarray(x).reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        k = sparse_k(n, density)
        if k >= n:
            idx = jnp.arange(n, dtype=jnp.int32)
            kept = flat
            top_mag = jnp.max(jnp.abs(flat), initial=0.0)
        else:
            top_mag_all, idx = jax.lax.top_k(jnp.abs(flat), k)
            idx = idx.astype(jnp.int32)
            kept = flat[idx]
            top_mag = top_mag_all[0]
        scale = jnp.maximum(top_mag, 1e-12) / 127.0
        q = jnp.clip(jnp.round(kept / scale), -127, 127).astype(jnp.int8)
        return {"idx": idx, "q": q, "scale": scale.astype(jnp.float32)}

    return {SPARSE_FORMAT_KEY: np.int32(SPARSE_FORMAT_TOPK8),
            "leaves": jax.tree_util.tree_map(leaf, delta)}


def _walk_state_dict(tree, path=()):
    """Yield (path tuple, leaf) for a nested state dict."""
    if isinstance(tree, dict):
        for key in sorted(tree):
            yield from _walk_state_dict(tree[key], path + (key,))
    else:
        yield path, tree


# kept-value dtypes a packed entry's "q" may carry: int8 (the quantized
# wire) or f32 (--wire-quant none — kept values ship unquantized, scale
# pinned to 1). Anything else is a hostile substitution (f64 parses at
# 8x the advertised bytes) and fails validation.
_PACKED_Q_DTYPES = (np.int8, np.float32)


def _validate_packed_entry(entry, n: int, *,
                           q_dtypes: tuple = (np.int8,)) -> tuple | None:
    """Field-wise validation of one top-k packed leaf entry
    ``{"idx", "q", "scale"}`` against a template leaf of ``n`` elements —
    everything an attacker controls: key set, dtypes (idx int32, q in
    ``q_dtypes``, scale f32 scalar), k <= n, finite non-negative scale,
    index bounds. Returns host ``(idx, q, scale)`` or None. Shared by the
    sparse8 densifier (int8 q only, its historical contract) and the v2
    packed wire (int8 or f32 kept values), so the formats cannot drift
    apart in what they accept."""
    if not isinstance(entry, dict) or set(entry) != {"idx", "q", "scale"}:
        return None
    idx, q, scale = (np.asarray(entry["idx"]), np.asarray(entry["q"]),
                     np.asarray(entry["scale"]))
    if (idx.dtype != np.int32 or q.dtype not in q_dtypes
            or scale.dtype != np.float32):
        return None
    if idx.ndim != 1 or q.ndim != 1 or scale.shape != ():
        return None
    if not np.isfinite(scale) or scale < 0:
        # every honest encoder emits scale >= 0 (max|kept|/127, or the
        # pinned 1.0 under quant="none"); a negative scale would flip the
        # sign of max|q|*scale in the packed magnitude screen and smuggle
        # arbitrarily large decoded values past the max_delta_abs cap
        return None
    if idx.shape[0] == 0 and q.shape[0] == n and n > 0:
        # DENSE-form entry (k == n): the index array would be arange(n),
        # pure redundancy at 4 bytes/coordinate — below-cutoff tensors
        # ship empty-idx + full q instead (1 byte/element under int8,
        # vs 5 for the indexed spelling)
        return idx, q, scale
    if q.shape != idx.shape or idx.shape[0] > n:
        return None
    if idx.shape[0] and (idx.min() < 0 or idx.max() >= n):
        return None
    return idx, q, scale


def _densify_packed_entry(idx, q, scale, shape) -> np.ndarray:
    """Validated entry -> dense f32 host array. Duplicate indices resolve
    last-wins (deterministic; screens run on the result regardless)."""
    n = int(np.prod(shape, dtype=np.int64))
    if idx.shape[0] == 0 and q.shape[0] == n and n > 0:
        # dense-form entry (empty idx, full q — see _validate_packed_entry)
        return (q.astype(np.float32) * float(scale)).reshape(shape)
    dense = np.zeros((n,), np.float32)
    dense[idx] = q.astype(np.float32) * float(scale)
    return dense.reshape(shape)


def _packed_tree_fields(leaves, template, *, q_dtypes: tuple = (np.int8,)):
    """Validate a packed-leaves tree against ``template`` leaf-by-leaf:
    path parity (each template leaf maps to exactly one
    ``{"idx","q","scale"}`` entry), then :func:`_validate_packed_entry`
    per entry. Returns ``[(path, shape, (idx, q, scale)), ...]`` in
    template walk order, or None on any mismatch — the one validator
    behind the sparse8 densifier, the v2 packed densifier, and the
    packed-form admission screen, so a payload accepted by one is
    accepted by all."""
    import flax.serialization as flax_ser

    if not isinstance(leaves, dict):
        return None
    t_flat = list(_walk_state_dict(flax_ser.to_state_dict(template)))
    s_by_parent: dict = {}
    for path, leaf in _walk_state_dict(leaves):
        if len(path) < 1:
            return None
        s_by_parent.setdefault(path[:-1], {})[path[-1]] = leaf
    if len(s_by_parent) != len(t_flat):
        return None
    out = []
    for path, t_leaf in t_flat:
        entry = s_by_parent.get(path)
        if entry is None:
            return None
        fields = _validate_packed_entry(
            entry, int(np.prod(np.shape(t_leaf), dtype=np.int64)),
            q_dtypes=q_dtypes)
        if fields is None:
            return None
        out.append((path, np.shape(t_leaf), fields))
    return out


def densify_sparse_delta(sparse: Params, template: Params) -> Params:
    """sparse8 wire tree -> dense f32 HOST delta shaped like ``template``.

    Validates everything an attacker controls: format marker, leaf-path
    parity with the template, dtypes (int32/int8/f32 pinned), k <= n,
    and index bounds. Returns None on any mismatch — same contract as
    the other wire-format decoders in the fetch try-chain. Duplicate
    indices resolve last-wins (deterministic; the magnitude/finiteness
    screens run on the densified tree regardless)."""
    import flax.serialization as flax_ser

    if not isinstance(sparse, dict):
        return None
    # The marker is attacker-controlled bytes: a string/array/NaN marker
    # must read as "not sparse8", not raise out of the decoder (a raised
    # TypeError here used to escape the fetch try-chain and abort the
    # whole scoring round — one hostile artifact silencing every miner).
    marker = sparse.get(SPARSE_FORMAT_KEY)
    try:
        marker_arr = np.asarray(marker)
        if marker_arr.shape != () or not np.issubdtype(
                marker_arr.dtype, np.integer):
            return None
        if int(marker_arr) != SPARSE_FORMAT_TOPK8:
            return None
    except (TypeError, ValueError):
        return None
    leaves = sparse.get("leaves")
    if not isinstance(leaves, dict) or set(sparse) != {
            SPARSE_FORMAT_KEY, "leaves"}:
        return None
    # sparse8 pins q to int8 exactly (its historical wire contract); the
    # v2 packed wire additionally admits f32 kept values (--wire-quant)
    fields = _packed_tree_fields(leaves, template, q_dtypes=(np.int8,))
    if fields is None:
        return None
    return _densify_fields(fields, template)


def _densify_fields(fields, template) -> Params:
    """Validated ``_packed_tree_fields`` output -> dense f32 host tree
    shaped like ``template``."""
    import flax.serialization as flax_ser

    out_state = flax_ser.to_state_dict(template)
    for path, shape, entry_fields in fields:
        node = out_state
        for key in path[:-1]:
            node = node[key]
        node[path[-1]] = _densify_packed_entry(*entry_fields, shape)
    return flax_ser.from_state_dict(template, out_state)


# ---------------------------------------------------------------------------
# Wire v2: packed per-layer top-k form (the shard-addressed publication
# channel). Same per-leaf layout as sparse8 ({"idx","q","scale"}) and the
# same top-k/quantization math, but (a) the tree stays split per WIRE
# TENSOR so engine/publish.py can ship each layer as its own
# content-addressed shard and engine/ingest.py can dedupe/fetch at shard
# granularity, (b) the encoder carries an ERROR-FEEDBACK residual, and
# (c) the cohort screen runs directly on the packed form (no densify).
#
# On error feedback vs the replace-don't-accumulate rule above: v1
# artifacts replace each other, so carrying a residual into the next v1
# push would re-add a superseded push's rounding error. The v2 regime is
# different in kind: top-k sparsification DROPS coordinates outright
# (not rounds them), and a coordinate that stays small forever would
# otherwise never ship at all — the residual accumulates exactly that
# dropped mass until it crosses the top-k threshold, so repeated lossy
# publishes converge on the true cumulative delta instead of drifting
# (the NeuronFabric Local-Adam regime: fewer, fatter, compressed
# publishes). The residual lives at the MINER and resets on base pulls
# (the cumulative delta it tracks resets there too).
# ---------------------------------------------------------------------------

WIRE_V2_KEY = "__wire_v2__"
WIRE_V2_FORMAT = 2
# --wire-quant vocabulary: int8 kept values (scale = max|kept|/127, the
# sparse8 math) or unquantized f32 kept values (scale pinned to 1)
WIRE_QUANTS = ("int8", "none")


def is_packed_entry(node) -> bool:
    """True for one packed per-tensor entry ``{"idx","q","scale"}`` (the
    is_leaf predicate for tree_map/tree_leaves over packed trees)."""
    return isinstance(node, dict) and set(node) == {"idx", "q", "scale"}


def is_packed_v2(tree) -> bool:
    """True when ``tree`` is a v2 packed delta (marker + leaves keys and
    an integer format-2 marker). Defensive like the sparse8 marker check:
    hostile marker types read as "not v2", never raise."""
    if not isinstance(tree, dict) or set(tree) != {WIRE_V2_KEY, "leaves"}:
        return False
    try:
        m = np.asarray(tree[WIRE_V2_KEY])
        return (m.shape == () and np.issubdtype(m.dtype, np.integer)
                and int(m) == WIRE_V2_FORMAT)
    except (TypeError, ValueError):
        return False


def pack_delta_v2(delta: Params, *, density: float = 1.0 / 64.0,
                  quant: str = "int8", residual: Params | None = None
                  ) -> tuple[Params, Params]:
    """Float delta -> (v2 packed tree, new error-feedback residual).

    Per leaf: top-k by |value| (``sparse_k`` — the sparse8 selection, so
    the parity pin vs ``sparsify_delta`` holds exactly), kept values
    int8-quantized against the tensor's own max (or shipped f32 under
    ``quant="none"``). ``residual`` is the previous publish's unsent
    mass, ADDED to the delta before selection; the returned residual is
    ``(delta + residual) - decode(packed)`` — what this publish still
    failed to ship. Pass ``residual=None`` for a residual of zeros (the
    first publish, and the stateless reference spelling the parity test
    pins). Jittable: k is static per leaf, both outputs are fresh
    buffers."""
    if not 0.0 < density <= 1.0:
        raise ValueError(f"density must be in (0, 1], got {density}")
    if quant not in WIRE_QUANTS:
        raise ValueError(f"quant must be one of {WIRE_QUANTS}, got {quant!r}")

    def leaf(x, r):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            raise ValueError(
                "pack_delta_v2: non-float leaf of dtype "
                f"{jnp.asarray(x).dtype} — the v2 wire covers all-float "
                "delta trees only")
        shape = jnp.shape(x)
        flat = jnp.asarray(x).reshape(-1).astype(jnp.float32)
        if r is not None:
            flat = flat + jnp.asarray(r).reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        k = sparse_k(n, density)
        dense_form = k >= n
        if dense_form:
            # DENSE-form entry: empty idx, full q (the idx array would be
            # arange(n) — 4 redundant bytes per coordinate on exactly the
            # below-cutoff tensors where every coordinate ships).
            # initial=0 keeps the max defined on zero-element leaves
            idx = jnp.zeros((0,), jnp.int32)
            kept = flat
            top_mag = jnp.max(jnp.abs(flat), initial=0.0)
        else:
            top_mag_all, idx = jax.lax.top_k(jnp.abs(flat), k)
            idx = idx.astype(jnp.int32)
            kept = flat[idx]
            top_mag = top_mag_all[0]
        if quant == "int8":
            scale = jnp.maximum(top_mag, 1e-12) / 127.0
            q = jnp.clip(jnp.round(kept / scale), -127, 127).astype(jnp.int8)
            decoded = q.astype(jnp.float32) * scale
        else:
            scale = jnp.asarray(1.0, jnp.float32)
            q = kept
            decoded = kept
        if dense_form:
            res = (flat - decoded).reshape(shape)
        else:
            # top-k indices are unique: scatter-add == flat - densify
            res = flat.at[idx].add(-decoded).reshape(shape)
        return {"idx": idx, "q": q,
                "scale": scale.astype(jnp.float32)}, res

    leaves, treedef = jax.tree_util.tree_flatten(delta)
    rleaves = (jax.tree_util.tree_leaves(residual)
               if residual is not None else [None] * len(leaves))
    if len(rleaves) != len(leaves):
        raise ValueError("pack_delta_v2: residual/delta structure mismatch")
    entries, res = [], []
    for x, r in zip(leaves, rleaves):
        e, rr = leaf(x, r)
        entries.append(e)
        res.append(rr)
    packed = {WIRE_V2_KEY: jnp.asarray(WIRE_V2_FORMAT, jnp.int32),
              "leaves": jax.tree_util.tree_unflatten(treedef, entries)}
    return packed, jax.tree_util.tree_unflatten(treedef, res)


def packed_matches(packed: Params, base: Params) -> bool:
    """Admission check for an untrusted packed v2 tree: marker, per-leaf
    path parity with ``base``, pinned field dtypes, k <= n, finite
    scales, index bounds — the packed analogue of ``shapes_match``
    (validated field-by-field because k varies per publisher, so there
    is no fixed template to restore against)."""
    if not is_packed_v2(packed):
        return False
    try:
        return _packed_tree_fields(packed["leaves"], base,
                                   q_dtypes=_PACKED_Q_DTYPES) is not None
    except (TypeError, ValueError, KeyError):
        return False


def densify_packed_v2(packed: Params, template: Params) -> Params:
    """v2 packed tree -> dense f32 HOST delta shaped like ``template``,
    or None on any validation failure (same contract as
    ``densify_sparse_delta``; accepts int8 AND f32 kept values)."""
    if not is_packed_v2(packed):
        return None
    # host phase in the device observatory: full-tensor writes per
    # contribution — the measured cost the ROADMAP's fused
    # dequant-scatter-add kernel is meant to delete
    with devprof.track("delta.densify"):
        try:
            fields = _packed_tree_fields(packed["leaves"], template,
                                         q_dtypes=_PACKED_Q_DTYPES)
        except (TypeError, ValueError, KeyError):
            return None
        if fields is None:
            return None
        return _densify_fields(fields, template)


def packed_layer_entries(packed: Params) -> dict[str, dict]:
    """Host split of a packed v2 tree into its shard units: one
    ``"a/b/c" -> {"idx","q","scale"}`` (np arrays) per wire tensor, keys
    "/"-joined state-dict paths — the layer keys the shard manifest is
    addressed by (serialization.build_wire_manifest). Publisher-side on
    its OWN tree, so malformed input raises instead of returning None."""
    import flax.serialization as flax_ser

    if not is_packed_v2(packed):
        raise ValueError("packed_layer_entries: not a v2 packed tree")
    by_parent: dict = {}
    for path, leaf in _walk_state_dict(
            flax_ser.to_state_dict(packed["leaves"])):
        if any("/" in str(k) for k in path):
            raise ValueError(f"packed_layer_entries: path component with "
                             f"'/' in {path!r} would make layer keys "
                             "ambiguous")
        by_parent.setdefault(path[:-1], {})[path[-1]] = np.asarray(
            jax.device_get(leaf))
    return {"/".join(str(k) for k in p): e for p, e in by_parent.items()}


def packed_from_layer_entries(entries: dict[str, dict]) -> Params:
    """Inverse of ``packed_layer_entries``: reassemble shard entries
    (ingest side, keys from an UNTRUSTED manifest) into a v2 packed tree.
    Purely structural — colliding/hostile keys produce a tree that then
    fails ``packed_matches`` against the template, never an exception
    here."""
    nested: dict = {}
    for key, entry in entries.items():
        parts = str(key).split("/")
        node = nested
        ok = True
        for p in parts[:-1]:
            nxt = node.setdefault(p, {})
            if not isinstance(nxt, dict):
                ok = False
                break
            node = nxt
        if ok:
            node[parts[-1]] = entry
    return {WIRE_V2_KEY: np.int32(WIRE_V2_FORMAT), "leaves": nested}


# ---------------------------------------------------------------------------
# Packed-form merge: scatter-add of idx/q*scale directly into a running
# aggregate. The averager-side half of the v2 wire — a sub-averager (or a
# packed-fleet flat averager) folds M submissions into ONE accumulator
# tree, one miner at a time, so device memory stays O(params + k) and the
# dense M x params stack of stack_deltas never exists. Compile cost is
# bounded by the distinct (leaf-shape, k) signatures in the fleet: every
# miner at the same density shares one compiled accumulate program
# (sparse_k is deterministic in (n, density)).
# ---------------------------------------------------------------------------

def _accum_packed(acc_leaves, entries, w):
    """acc leaves + w * decode(entries), leafwise. The decode is the
    densifier's arithmetic — ``w * (q_f32 * scale)`` — scattered at idx
    (or added wholesale for dense-form entries), so the result matches
    ``acc + w * densify_packed_v2(...)`` to multiply-add fusion
    tolerance (XLA may emit FMA for ``a + w*x``; ~1 ulp) for honest
    (unique-index) encodings; hostile duplicate indices sum here where
    the densifier resolves last-wins (both deterministic, both screened
    upstream). Jittable: dense-form vs indexed is a static shape test."""
    out = []
    for a, e in zip(acc_leaves, entries):
        flat = a.reshape(-1)
        idx, q, scale = e["idx"], e["q"], e["scale"]
        contrib = w * (q.astype(flat.dtype) * scale)
        n = flat.shape[0]
        if idx.shape[0] == 0 and q.shape[0] == n and n > 0:
            flat = flat + contrib        # dense-form entry (k == n)
        else:
            flat = flat.at[idx].add(contrib)
        out.append(flat.reshape(a.shape))
    return out


_accum_packed_jit = devprof.wrap(
    "delta.accumulate", jax.jit(_accum_packed), bucket="packed")


def _accum_packed_kernel(acc_leaves, entries, w):
    """Kernel-backed twin of :func:`_accum_packed`: indexed-form entries
    route through the fused dequantize->scatter-add Pallas kernel
    (ops/dequant_scatter.py) whose accumulator is aliased in place —
    bytes written per contribution drop from O(n) (the functional
    ``.at[idx].add`` copy) to O(k). Leaves the kernel declines (too big
    for VMEM, empty idx) keep the XLA spelling INSIDE the same program,
    so the output is identical leaf-for-leaf either way (parity pinned
    in tests/test_dequant_scatter.py)."""
    from .ops import dequant_scatter as _dsc
    out = []
    for a, e in zip(acc_leaves, entries):
        flat = a.reshape(-1)
        idx, q, scale = e["idx"], e["q"], e["scale"]
        n = flat.shape[0]
        if idx.shape[0] == 0 and q.shape[0] == n and n > 0:
            flat = flat + w * (q.astype(flat.dtype) * scale)
        else:
            got = _dsc.dequant_scatter_add(flat, idx, q, w * scale)
            if got is None:   # static decline (shape/VMEM budget)
                flat = flat.at[idx].add(w * (q.astype(flat.dtype) * scale))
            else:
                flat = got
        out.append(flat.reshape(a.shape))
    return out


# built lazily: donation (the cross-call half of the in-place story — a
# donated accumulator lets XLA alias the kernel's input_output_aliases
# chain across contributions) is backend-dependent, and probing the
# backend at import time would force backend init on every importer
_ACCUM_KERNEL_PROG = None


def _accum_packed_kernel_prog():
    global _ACCUM_KERNEL_PROG
    if _ACCUM_KERNEL_PROG is None:
        try:
            donate = (0,) if jax.default_backend() in ("tpu", "axon") \
                else ()
        except Exception:
            donate = ()
        _ACCUM_KERNEL_PROG = devprof.wrap(
            "delta.dequant_scatter",
            jax.jit(_accum_packed_kernel, donate_argnums=donate),
            bucket="packed")
    return _ACCUM_KERNEL_PROG


def _accum_dense(acc, d, w):
    return jax.tree_util.tree_map(
        lambda a, x: a + w * x.astype(a.dtype), acc, d)


_accum_dense_jit = devprof.wrap(
    "delta.accumulate", jax.jit(_accum_dense), bucket="dense")


def accumulate_delta(acc: Params, delta: Params, weight) -> Params:
    """``acc + weight * delta`` where ``delta`` is a dense tree OR a v2
    packed tree (already admitted via ``packed_matches`` — entry order
    and element counts are trusted to line up with ``acc``). Packed
    submissions accumulate by per-tensor scatter-add of ``idx/q*scale``
    without ever densifying; dense ones by one fused add. Both run as
    ONE jitted program per call with the weight traced, so repeated
    rounds and varying weights reuse the compiled programs."""
    w = jnp.asarray(weight, jnp.float32)
    if is_packed_v2(delta):
        from .ops import dequant_scatter as _dsc
        leaves, treedef = jax.tree_util.tree_flatten(acc)
        entries = jax.tree_util.tree_leaves(delta["leaves"],
                                            is_leaf=is_packed_entry)
        if len(entries) != len(leaves):
            raise ValueError(
                f"accumulate_delta: {len(entries)} packed entries for a "
                f"{len(leaves)}-leaf accumulator (run packed_matches "
                "before accumulating)")
        prog = _accum_packed_kernel_prog() if _dsc.enabled() \
            else _accum_packed_jit
        return jax.tree_util.tree_unflatten(
            treedef, prog(leaves, entries, w))
    return _accum_dense_jit(acc, delta, w)


def aggregate_deltas(template: Params, deltas: Sequence[Params],
                     weights) -> Params:
    """``sum_i weights[i] * delta_i`` over a HOST list of mixed
    dense/packed submissions with O(params) device memory: one f32
    accumulator, one contribution folded at a time
    (:func:`accumulate_delta`) — the sub-averager's partial-aggregate
    body (engine/hier_average.py) and the packed twin of
    ``chunked_weighted_merge`` (which needs dense trees to stack).
    ``weights`` are used AS GIVEN (no normalization here — callers
    normalize over the real cohort via normalized_merge_weights)."""
    if not deltas:
        raise ValueError("aggregate_deltas: empty delta list")
    weights = np.asarray(jax.device_get(weights), np.float32).reshape(-1)
    if weights.shape[0] != len(deltas):
        raise ValueError(f"{weights.shape[0]} weights for "
                         f"{len(deltas)} deltas")
    acc = jax.tree_util.tree_map(
        lambda x: jnp.zeros(np.shape(x), jnp.float32), template)
    for d, w in zip(deltas, weights):
        acc = accumulate_delta(acc, d, w)
    return acc


def _packed_screen_stats(*packed_leaves) -> tuple[jax.Array, jax.Array]:
    """Per-tree (finite flag, max |decoded value|) for a cohort of packed
    v2 leaves-trees — the packed twin of ``_cohort_screen_stats``, fused
    the same way. No densify: int8 kept values are finite by
    construction, so finiteness is the scales' (plus f32 kept values',
    under quant="none"); the decoded max is ``max|q| * |scale|`` per
    tensor exactly — the abs covers the scale too, not just q, so a
    hostile negative scale (rejected at admission, but this program must
    not depend on that) cannot drive the verdict negative and under the
    magnitude cap. Matches the dense screen on the densified tree.
    Returns ([K] bool, [K] f32)."""
    fins, maxs = [], []
    for leaves in packed_leaves:
        entries = jax.tree_util.tree_leaves(leaves, is_leaf=is_packed_entry)
        flags, mags = [], []
        for e in entries:
            flags.append(jnp.any(~jnp.isfinite(e["scale"])))
            if jnp.issubdtype(jnp.asarray(e["q"]).dtype, jnp.inexact):
                flags.append(jnp.any(~jnp.isfinite(e["q"])))
            if e["q"].size:
                mags.append(jnp.max(jnp.abs(e["q"].astype(jnp.float32)))
                            * jnp.abs(e["scale"]))
        fins.append(jnp.logical_not(jnp.any(jnp.stack(flags)))
                    if flags else jnp.asarray(True))
        maxs.append(jnp.max(jnp.stack(mags)) if mags
                    else jnp.asarray(0.0, jnp.float32))
    return jnp.stack(fins), jnp.stack(maxs)


_packed_screen_stats_jit = devprof.wrap(
    "delta.screen_packed", jax.jit(_packed_screen_stats),
    bucket=lambda a, kw: len(a))  # screen arity (bucket-padded chunk)


def sparse_delta_from_bytes(data: bytes, template: Params,
                            *, max_bytes: int | None = None) -> Params:
    """Raw artifact bytes -> dense delta if they are a valid sparse8
    artifact, else None (the fetch try-chain's sparse attempt)."""
    from . import serialization as ser

    try:
        kw = {} if max_bytes is None else {"max_bytes": max_bytes}
        raw = ser.from_msgpack(data, None, **kw)
    except ser.PayloadError:
        return None
    # Belt-and-braces: densify validates field-by-field and returns None,
    # but hostile bytes must fail per-miner even if a validation gap lets
    # an exception through (same contract as the other decoders).
    try:
        return densify_sparse_delta(raw, template)
    except (TypeError, ValueError, KeyError, IndexError):
        return None
