// Native sequence packer: the hot host-side loop of the data pipeline.
//
// Exact behavioral twin of the pure-Python packer in data/packing.py
// (greedy fill, per-chunk position restart, successor-in-segment loss mask,
// fresh segment id for the padding tail) — the Python generator remains the
// correctness oracle and the fallback; this keeps a single v5e chip
// (~100k tok/s training) fed from one CPU core instead of several.
//
// C ABI only (loaded via ctypes): no Python.h, no build-time dependency on
// the interpreter.

#include <cstdint>
#include <cstring>

extern "C" {

// Pack concatenated documents into fixed-length rows.
//
// tokens    : all documents back to back, int32
// doc_lens  : length of each document, int64[n_docs]
// seq_len   : row width
// drop_remainder : when 0, a trailing partial row is emitted
// ids/seg/pos/mask : caller-allocated [rows_cap, seq_len] outputs
// rows_cap  : capacity in rows; the function never writes beyond it
//
// Returns the number of rows written, or -1 if rows_cap was insufficient
// (the caller sizes rows_cap = total_tokens/seq_len + 1, which always
// suffices; -1 is a defensive contract, not an expected path).
int64_t dt_pack(const int32_t* tokens, const int64_t* doc_lens,
                int64_t n_docs, int64_t seq_len, int drop_remainder,
                int32_t* ids, int32_t* seg, int32_t* pos, float* mask,
                int64_t rows_cap) {
    if (seq_len <= 0 || rows_cap < 0) return -1;
    int64_t row = 0;       // rows completed
    int64_t fill = 0;      // tokens in the current row
    int32_t seg_id = 0;    // next segment id within the current row
    int64_t consumed = 0;  // global token cursor

    auto row_base = [&](int64_t r) { return r * seq_len; };

    // zero the first row lazily as we go: every cell of a completed row is
    // written exactly once below, except the mask (cleared per chunk tail),
    // so clear mask/ids up front per row instead.
    auto begin_row = [&]() {
        if (row >= rows_cap) return false;
        int64_t b = row_base(row);
        std::memset(ids + b, 0, sizeof(int32_t) * seq_len);
        std::memset(seg + b, 0, sizeof(int32_t) * seq_len);
        std::memset(pos + b, 0, sizeof(int32_t) * seq_len);
        std::memset(mask + b, 0, sizeof(float) * seq_len);
        return true;
    };
    if (!begin_row()) return n_docs == 0 ? 0 : -1;

    for (int64_t d = 0; d < n_docs; ++d) {
        int64_t remaining = doc_lens[d];
        while (remaining > 0) {
            int64_t space = seq_len - fill;
            int64_t take = remaining < space ? remaining : space;
            int64_t b = row_base(row) + fill;
            std::memcpy(ids + b, tokens + consumed,
                        sizeof(int32_t) * take);
            for (int64_t i = 0; i < take; ++i) {
                seg[b + i] = seg_id;
                pos[b + i] = static_cast<int32_t>(i);  // parity: restart per chunk
            }
            for (int64_t i = 0; i + 1 < take; ++i) mask[b + i] = 1.0f;
            consumed += take;
            remaining -= take;
            fill += take;
            if (fill == seq_len) {
                ++row;
                fill = 0;
                seg_id = 0;
                if (!begin_row()) {
                    // out of capacity; only acceptable if nothing remains
                    if (remaining == 0 && d == n_docs - 1) return row;
                    return -1;
                }
            } else {
                ++seg_id;
            }
        }
    }
    if (fill > 0 && !drop_remainder) {
        int64_t b = row_base(row);
        for (int64_t i = fill; i < seq_len; ++i) seg[b + i] = seg_id + 1;
        ++row;
    }
    return row;
}

}  // extern "C"
