"""Native (C++) host-side components, loaded via ctypes.

The TPU compute path is JAX/XLA/Pallas; what native code buys here is the
*host* side of the pipeline — the data-packing loop that has to outrun the
chip. Components are built on first use with the system toolchain (g++ is
part of this image), cached as shared objects next to their sources, and
every consumer has a pure-Python fallback, so an environment without a
compiler still runs everything (slower).

Loader contract:
- ``load(name)`` returns a ctypes.CDLL or None (never raises for missing
  toolchain / failed build; the failure is logged once).
- builds are atomic (tmp + rename) so concurrent first-use races are safe.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_FAILED: set[str] = set()


def _so_path(name: str) -> str:
    return os.path.join(_DIR, f"lib{name}.so")


def build(name: str) -> str | None:
    """Compile native/<name>.cpp -> native/lib<name>.so; returns the path or
    None on failure. Skips the build when the .so is newer than the source."""
    src = os.path.join(_DIR, f"{name}.cpp")
    out = _so_path(name)
    if not os.path.exists(src):
        return None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: concurrent builders race harmlessly
        return out
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("native build of %s failed (%s); using the Python "
                       "fallback", name, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load(name: str) -> ctypes.CDLL | None:
    """Build-if-needed and dlopen; None (once-logged) on any failure."""
    if name in _FAILED:
        return None
    path = build(name)
    if path is None:
        _FAILED.add(name)
        return None
    try:
        return ctypes.CDLL(path)
    except OSError as e:
        logger.warning("failed to load %s: %s", path, e)
        _FAILED.add(name)
        return None
