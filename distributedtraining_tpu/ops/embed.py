"""Embedding lookup with a mesh-aware backward.

The gather forward is optimal everywhere. Its default VJP, however, is a
scatter-add, and on meshes with BOTH dp > 1 and fsdp > 1 GSPMD must
reshard the incoming [B, T, E] cotangent from batch sharding
(('dp','fsdp') on dim 0, enumerated row-major) onto the table's
embed/fsdp axis (enumerated fsdp-major) — a transfer the SPMD
partitioner cannot express on that device order, so it falls back to
"involuntary full rematerialization": the whole cotangent is replicated
to every device and re-partitioned, each step.

The one-hot einsum spelling of the same backward is a plain matmul
(contract over batch x seq): every device computes a partial [V, E]
gradient from its LOCAL cotangent shard and GSPMD reduces it straight
onto the table sharding — no cotangent reshard, and the work rides the
MXU. The one-hot tensor only exists inside the backward pass and fuses
into the matmul. This is the standard TPU embedding trick (MaxText's
iota-embed); the reference has no counterpart (single-device PyTorch).

``embed_lookup`` picks the spelling at trace time from the ambient mesh
(the engines activate their mesh while tracing): scatter stays the
default everywhere the reshard is expressible (single device, dp-only,
fsdp-only), since the matmul backward costs ~B*T*V*E extra FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


_warned_no_thread_resources = False


def _ambient_mesh_needs_matmul_bwd() -> bool:
    """True when the mesh active during tracing has both dp>1 and fsdp>1 —
    the configuration whose gather-backward reshard GSPMD cannot express
    (see module docstring)."""
    try:
        try:
            # the `with mesh:` context reader; public spelling
            # (jax.interpreters.pxla.thread_resources) deprecated in 0.8.2
            # with no public replacement for the legacy context
            from jax._src.mesh import thread_resources
        except ImportError:  # pragma: no cover — older jax
            from jax.interpreters.pxla import thread_resources
    except ImportError:  # pragma: no cover — future jax relocation
        # both private spellings gone: degrade to the default scatter
        # backward (correct everywhere, slower on dp x fsdp meshes)
        # instead of raising out of every embedding TRACE — an import
        # error here would take down single-device runs that never
        # needed the probe at all
        global _warned_no_thread_resources
        if not _warned_no_thread_resources:
            _warned_no_thread_resources = True
            import logging
            logging.getLogger(__name__).warning(
                "jax no longer exposes thread_resources at either known "
                "path; embedding backward keeps the scatter spelling "
                "(involuntary-remat risk returns on dp x fsdp meshes)")
        return False
    mesh = thread_resources.env.physical_mesh
    if mesh.empty:
        return False
    shape = dict(mesh.shape)
    return shape.get("dp", 1) > 1 and shape.get("fsdp", 1) > 1


import functools


@functools.lru_cache(maxsize=None)
def _take_matmul_bwd(vocab: int, dtype_name: str):
    """custom_vjp gather specialized on the (static) table vocab/dtype."""

    @jax.custom_vjp
    def take(table, ids):
        return jnp.take(table, ids, axis=0, mode="clip")

    def fwd(table, ids):
        return take(table, ids), ids

    def bwd(ids, g):
        onehot = jax.nn.one_hot(ids, vocab, dtype=g.dtype)
        dtable = jnp.einsum("...v,...e->ve", onehot, g)
        return (dtable.astype(dtype_name),
                np.zeros(ids.shape, jax.dtypes.float0))  # int ids: no tangent

    take.defvjp(fwd, bwd)
    return take


def embed_lookup(table: jax.Array, ids: jax.Array) -> jax.Array:
    """``table[ids]`` with the backward spelling chosen for the ambient
    mesh. Forward is a gather either way."""
    if _ambient_mesh_needs_matmul_bwd():
        return _take_matmul_bwd(table.shape[0], str(table.dtype))(table, ids)
    # mode="clip" preserves `table[ids]` getitem semantics: jnp.take's
    # default is "fill", which turns an out-of-range index (e.g. eval at
    # T > n_positions) into NaN rows instead of the clamped lookup the
    # indexing spelling always did. (The matmul backward's one-hot zeroes
    # OOB rows' gradients; OOB positions are a config error either way.)
    return jnp.take(table, ids, axis=0, mode="clip")
