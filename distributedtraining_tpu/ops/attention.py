"""Causal self-attention for TPU.

Implementations:
- "dense": einsum QK^T -> fp32 softmax -> PV. XLA fuses this well on TPU for
  the reference's sequence lengths (64-512 tokens); it is the default and the
  correctness oracle for the fancier paths.
- "flash": Pallas blockwise-softmax kernel (ops/flash_attention.py), used for
  long sequences where the [T, T] score matrix stops fitting in VMEM.
- ring attention for sequence-parallel meshes lives in ops/ring_attention.py
  (it calls back into these per-block primitives).

Supports padding masks and packed-sequence segment ids (block-diagonal
attention), which the data pipeline uses to avoid the reference's pad-to-64
token waste (neurons/miner.py:70).

Shapes: q, k, v are [batch, seq, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative in bf16-safe range (bf16 max ~3.4e38, fine)


def make_causal_mask(q_len: int, kv_len: int | None = None,
                     *, q_offset: int = 0) -> jax.Array:
    """Boolean [q_len, kv_len] mask, True = may attend.

    ``q_offset`` shifts query positions — used by ring attention where the
    local query block sits at a global offset relative to the key block.
    """
    kv_len = q_len if kv_len is None else kv_len
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return q_pos >= kv_pos


def combine_masks(causal: jax.Array,
                  attention_mask: Optional[jax.Array],
                  segment_ids: Optional[jax.Array],
                  kv_segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Fold padding + packing masks into the causal mask.

    attention_mask: [B, kv_len] with 1 = real token.
    segment_ids:    [B, q_len] packing ids; tokens attend only within their
                    own segment (block-diagonal).
    Returns [B, 1, q_len, kv_len] boolean.
    """
    mask = causal[None, None, :, :]
    if attention_mask is not None:
        mask = mask & attention_mask[:, None, None, :].astype(bool)
    if segment_ids is not None:
        kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
        same = segment_ids[:, :, None] == kv_seg[:, None, :]
        mask = mask & same[:, None, :, :]
    return mask


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array]) -> jax.Array:
    """Masked attention with fp32 softmax accumulation.

    q/k/v: [B, T, H, D] (any float dtype; scores accumulate in fp32).
    mask: broadcastable to [B, H, Tq, Tkv], True = attend.
    """
    depth = q.shape[-1]
    scale = depth ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


# dense materializes [B, H, T, T] scores; above this length a declined
# flash kernel falls back to the blockwise spelling instead, whose temp
# memory is O(B*H*bq*bk) — the same profile as the Pallas kernel
BLOCKWISE_FALLBACK_LEN = 1024


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        *,
                        attention_mask: Optional[jax.Array] = None,
                        segment_ids: Optional[jax.Array] = None,
                        block_q: int = 512,
                        block_kv: int = 512) -> jax.Array:
    """Causal attention as a double lax.scan over query/key blocks with an
    online softmax — the FlashAttention algorithm in portable lax (same
    streaming math as ring_attention._ring_body, but blocks come from a
    local reshape instead of an ICI ring).

    No [T, T] score matrix ever exists: peak temp is one [B, H, bq, bkv]
    tile, and ``jax.checkpoint`` on the inner step keeps the backward at
    the same profile (tiles recompute instead of being stashed per
    block). This is the memory-honest fallback when the Pallas flash
    kernel declines (CPU backends, odd shapes) and the spelling the AOT
    scale artifacts compile so their XLA memory analysis reflects the
    flash-kernel profile rather than a dense [T, T] blowup the TPU never
    pays. Masking matches combine_masks: causal + optional key padding
    mask + optional segment equality (packed sequences).
    """
    B, T, H, D = q.shape
    bq, bkv = min(block_q, T), min(block_kv, T)
    pad_q = (-T) % bq
    pad_kv = (-T) % bkv
    nq, nkv = (T + pad_q) // bq, (T + pad_kv) // bkv
    scale = D ** -0.5

    qf = (q.astype(jnp.float32) * scale)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # key validity: padding-mask AND in-bounds (scan blocks are static)
    kvalid = jnp.ones((B, T), bool) if attention_mask is None \
        else attention_mask.astype(bool)
    if pad_kv:
        kf = jnp.pad(kf, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        kvalid = jnp.pad(kvalid, ((0, 0), (0, pad_kv)))
    seg = segment_ids
    if seg is not None:
        qseg = jnp.pad(seg, ((0, 0), (0, pad_q)), constant_values=-1)
        kseg = jnp.pad(seg, ((0, 0), (0, pad_kv)), constant_values=-2)
        qseg = qseg.reshape(B, nq, bq).transpose(1, 0, 2)    # [nq, B, bq]
        kseg = kseg.reshape(B, nkv, bkv).transpose(1, 0, 2)  # [nkv, B, bkv]
    qb = qf.reshape(B, nq, bq, H, D).transpose(1, 0, 2, 3, 4)
    kb = kf.reshape(B, nkv, bkv, H, D).transpose(1, 0, 2, 3, 4)
    vb = vf.reshape(B, nkv, bkv, H, D).transpose(1, 0, 2, 3, 4)
    kvalid_b = kvalid.reshape(B, nkv, bkv).transpose(1, 0, 2)  # [nkv, B, bkv]

    def kv_tile_update(qi, q_tile, q_seg_tile, carry, kv):
        acc, m_prev, l_prev = carry
        ki, k_tile, v_tile, kv_ok, k_seg_tile = kv
        scores = jnp.einsum("bqhd,bkhd->bhqk", q_tile, k_tile)
        q_pos = qi * bq + jnp.arange(bq)
        k_pos = ki * bkv + jnp.arange(bkv)
        mask = q_pos[:, None] >= k_pos[None, :]          # causal
        mask = mask[None, :, :] & kv_ok[:, None, :]      # key padding
        if q_seg_tile is not None:
            mask = mask & (q_seg_tile[:, :, None] == k_seg_tile[:, None, :])
        scores = jnp.where(mask[:, None, :, :], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new[..., None])
        # exp(NEG_INF - m) underflows to 0 for any real m, but a FULLY
        # masked running max (m_new == NEG_INF) would turn masked entries
        # into exp(0) = 1 — zero them explicitly so dead rows (no visible
        # key after causal+padding+segment masking) emit exact 0, the
        # flash-kernel convention
        p = p * mask[:, None, :, :]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_tile)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        return acc, m_new, l_new

    def kv_step(qi, q_tile, q_seg_tile, carry, kv):
        # skip causally-dead blocks (every key strictly in the future of
        # every query of this tile): about half the tiles at long T. The
        # predicate is a per-iteration scalar, so lax.cond executes only
        # one branch instead of lowering to a select
        ki = kv[0]
        dead = ki * bkv > qi * bq + (bq - 1)
        new_carry = jax.lax.cond(
            dead, lambda c, _kv: c,
            lambda c, kv_: kv_tile_update(qi, q_tile, q_seg_tile, c, kv_),
            carry, kv)
        return new_carry, None

    kv_step = jax.checkpoint(kv_step, static_argnums=())

    def q_step(_, q_in):
        qi, q_tile, q_seg_tile = q_in
        acc0 = jnp.zeros((B, bq, H, D), jnp.float32)
        m0 = jnp.full((B, H, bq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, bq), jnp.float32)
        kvs = (jnp.arange(nkv), kb, vb, kvalid_b,
               kseg if seg is not None else jnp.zeros((nkv,)))
        (acc, m, l), _ = jax.lax.scan(
            lambda c, kv: kv_step(qi, q_tile, q_seg_tile, c, kv),
            (acc0, m0, l0), kvs)
        l = jnp.maximum(l, 1e-30)
        return None, acc / l.transpose(0, 2, 1)[..., None]

    q_in = (jnp.arange(nq), qb, qseg if seg is not None else jnp.zeros((nq,)))

    def q_step_wrap(c, q_in_):
        qi, q_tile, q_seg_tile = q_in_
        return q_step(c, (qi, q_tile,
                          q_seg_tile if seg is not None else None))

    # checkpoint the WHOLE q block: without it the outer scan stashes
    # every inner-scan carry for every q block (nq x nkv x [B,bq,H,D]);
    # with it the backward recomputes one q block's inner scan at a time
    _, out = jax.lax.scan(jax.checkpoint(q_step_wrap), None, q_in)
    out = out.transpose(1, 0, 2, 3, 4).reshape(B, T + pad_q, H, D)
    return out[:, :T].astype(q.dtype)


def cached_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     ctx_lens: jax.Array) -> jax.Array:
    """Decode-step attention for KV-cache generation (engine/serve.py).

    ``q`` is the current step's queries [B, Tq, H, D]; ``k``/``v`` are the
    PADDED cached context concatenated with the current step's keys/values
    [B, S + Tq, H, D], where S is the (bucket-padded) context capacity.
    ``ctx_lens`` [B] gives each row's REAL context length: context
    positions >= ctx_lens[b] are padding (dead pages of the paged KV
    pool) and masked out; the trailing Tq positions are the new tokens,
    causally masked among themselves and always visible to themselves.

    Same fp32-softmax math as ``dot_product_attention`` — padded keys hit
    the NEG_INF branch, whose exp underflows to exact 0, so garbage in
    dead cache slots cannot leak into the output.

    The mask is an iota compare folded into the score computation, not a
    materialized buffer: the old spelling concatenated two broadcast
    ``[B, Tq, S]``/``[B, Tq, Tq]`` boolean arrays into a ``[B, Tq, S+Tq]``
    mask per decode step — O(B·S) bytes written every token for a
    predicate XLA can fuse into the ``where`` on the scores for free.
    Identical mask semantics (pinned in tests/test_paged_attention.py):
    context positions valid below ``ctx_lens``, the trailing Tq fresh
    positions causal among themselves and always visible to themselves.
    """
    B, Tq, _, depth = q.shape
    S = k.shape[1] - Tq
    scale = depth ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    kv_pos = jnp.arange(S + Tq)[None, None, :]                # [1, 1, S+Tq]
    q_pos = jnp.arange(Tq)[None, :, None]                     # [1, Tq, 1]
    valid = (kv_pos < ctx_lens[:, None, None]) | (
        (kv_pos >= S) & (kv_pos - S <= q_pos))                # [B, Tq, S+Tq]
    scores = jnp.where(valid[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     *,
                     attention_mask: Optional[jax.Array] = None,
                     segment_ids: Optional[jax.Array] = None,
                     impl: str = "dense") -> jax.Array:
    """Causal self-attention entry point used by the models.

    impl: "dense" (XLA), "flash" (Pallas kernel when available, falls back
    to blockwise at long T / dense at short T on non-TPU backends),
    "blockwise" (portable lax flash — O(block^2) temps everywhere), "ring"
    (sequence-parallel over the sp mesh axis; needs set_ring_mesh and
    unmasked/unpacked inputs).
    """
    B, T, H, D = q.shape
    if impl == "ring" and attention_mask is None and segment_ids is None:
        from . import ring_attention as ring
        mesh, _ = ring.get_ring_mesh()
        if mesh is not None:
            return ring.ring_attention(q, k, v)
        # no mesh installed -> dense fallback below
    if impl == "blockwise":
        return blockwise_attention(q, k, v, attention_mask=attention_mask,
                                   segment_ids=segment_ids)
    if impl == "flash":
        from . import flash_attention
        out = flash_attention.flash_attention(
            q, k, v, attention_mask=attention_mask, segment_ids=segment_ids)
        if out is not None:
            return out
        if T >= BLOCKWISE_FALLBACK_LEN:
            # kernel declined (CPU backend): at long T the dense [T, T]
            # fallback would blow temp memory the TPU path never pays —
            # stream blocks instead
            return blockwise_attention(
                q, k, v, attention_mask=attention_mask,
                segment_ids=segment_ids)
        # short T: dense is faster off-TPU and the temps are tiny
    mask = combine_masks(make_causal_mask(T), attention_mask, segment_ids)
    return dot_product_attention(q, k, v, mask)
