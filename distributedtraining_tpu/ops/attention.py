"""Causal self-attention for TPU.

Implementations:
- "dense": einsum QK^T -> fp32 softmax -> PV. XLA fuses this well on TPU for
  the reference's sequence lengths (64-512 tokens); it is the default and the
  correctness oracle for the fancier paths.
- "flash": Pallas blockwise-softmax kernel (ops/flash_attention.py), used for
  long sequences where the [T, T] score matrix stops fitting in VMEM.
- ring attention for sequence-parallel meshes lives in ops/ring_attention.py
  (it calls back into these per-block primitives).

Supports padding masks and packed-sequence segment ids (block-diagonal
attention), which the data pipeline uses to avoid the reference's pad-to-64
token waste (neurons/miner.py:70).

Shapes: q, k, v are [batch, seq, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative in bf16-safe range (bf16 max ~3.4e38, fine)


def make_causal_mask(q_len: int, kv_len: int | None = None,
                     *, q_offset: int = 0) -> jax.Array:
    """Boolean [q_len, kv_len] mask, True = may attend.

    ``q_offset`` shifts query positions — used by ring attention where the
    local query block sits at a global offset relative to the key block.
    """
    kv_len = q_len if kv_len is None else kv_len
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return q_pos >= kv_pos


def combine_masks(causal: jax.Array,
                  attention_mask: Optional[jax.Array],
                  segment_ids: Optional[jax.Array],
                  kv_segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Fold padding + packing masks into the causal mask.

    attention_mask: [B, kv_len] with 1 = real token.
    segment_ids:    [B, q_len] packing ids; tokens attend only within their
                    own segment (block-diagonal).
    Returns [B, 1, q_len, kv_len] boolean.
    """
    mask = causal[None, None, :, :]
    if attention_mask is not None:
        mask = mask & attention_mask[:, None, None, :].astype(bool)
    if segment_ids is not None:
        kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
        same = segment_ids[:, :, None] == kv_seg[:, None, :]
        mask = mask & same[:, None, :, :]
    return mask


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array]) -> jax.Array:
    """Masked attention with fp32 softmax accumulation.

    q/k/v: [B, T, H, D] (any float dtype; scores accumulate in fp32).
    mask: broadcastable to [B, H, Tq, Tkv], True = attend.
    """
    depth = q.shape[-1]
    scale = depth ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     *,
                     attention_mask: Optional[jax.Array] = None,
                     segment_ids: Optional[jax.Array] = None,
                     impl: str = "dense") -> jax.Array:
    """Causal self-attention entry point used by the models.

    impl: "dense" (XLA), "flash" (Pallas kernel when available, falls back to
    dense on non-TPU backends), "ring" (sequence-parallel over the sp mesh
    axis; needs set_ring_mesh and unmasked/unpacked inputs).
    """
    B, T, H, D = q.shape
    if impl == "ring" and attention_mask is None and segment_ids is None:
        from . import ring_attention as ring
        mesh, _ = ring.get_ring_mesh()
        if mesh is not None:
            return ring.ring_attention(q, k, v)
        # no mesh installed -> dense fallback below
    if impl == "flash":
        from . import flash_attention
        out = flash_attention.flash_attention(
            q, k, v, attention_mask=attention_mask, segment_ids=segment_ids)
        if out is not None:
            return out
        # fall through to dense when the kernel declines (e.g. CPU backend)
    mask = combine_masks(make_causal_mask(T), attention_mask, segment_ids)
    return dot_product_attention(q, k, v, mask)
