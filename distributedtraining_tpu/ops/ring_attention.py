"""Ring attention: sequence-parallel causal attention over ICI.

Long-context path (Liu et al., "Ring Attention with Blockwise Transformers"):
the sequence axis is sharded over the mesh's ``sp`` axis; each device holds a
query block and streams the K/V blocks around the ring with ``ppermute``,
accumulating attention with an online softmax (running max + denominator, all
fp32). Peak activation memory per device is O(T/sp), and the K/V transfers
overlap compute around the ICI ring — no [T, T] score matrix ever exists.

Causality across blocks: query block q at global offset qo attends K/V block
at offset ko with a full mask when ko + block < qo, a triangular mask when
ko == qo, and contributes nothing when ko > qo (still computed, masked to
-inf — a static ring schedule keeps XLA happy; skipping would need dynamic
control flow).

Usage: the engine calls ``set_ring_mesh(mesh)`` once; models route here via
``causal_attention(..., impl="ring")`` when sequence parallelism is on. With
no mesh set (or sp == 1) the dense path runs instead.

The reference has no long-context support at all (max seq 512,
SURVEY.md §5); this is a capability extension required for the TPU build.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

NEG_INF = -1e9

_RING_MESH: Mesh | None = None
_RING_AXIS = "sp"


def set_ring_mesh(mesh: Mesh | None, axis: str = "sp") -> None:
    """Install the mesh used by impl="ring" attention (engine calls this)."""
    global _RING_MESH, _RING_AXIS
    _RING_MESH = mesh
    _RING_AXIS = axis


def get_ring_mesh() -> tuple[Mesh | None, str]:
    return _RING_MESH, _RING_AXIS


def _ring_body(q, k, v, *, axis: str, axis_size: int, t_local: int):
    """Per-device blockwise attention; q/k/v are local [B, Tl, H, D]."""
    idx = jax.lax.axis_index(axis)
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32) * scale
    B, Tl, H, D = q.shape

    q_pos = idx * t_local + jnp.arange(Tl)  # global query positions

    def step(s, carry):
        acc, m_prev, l_prev, k_cur, v_cur = carry
        src = (idx - s) % axis_size  # which block we currently hold
        k_pos = src * t_local + jnp.arange(Tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32))
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return acc, m_new, l_new, k_nxt, v_nxt

    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (acc0, m0, l0, k, v))
    # rows with no visible keys (can't happen causally, but keep the math
    # total) and normalization
    l = jnp.maximum(l, 1e-30)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, mesh: Mesh | None = None,
                   axis: str | None = None) -> jax.Array:
    """Causal ring attention; q/k/v are global [B, T, H, D] with T sharded
    over the sp axis (or replicated — shard_map partitions either way)."""
    mesh = mesh if mesh is not None else _RING_MESH
    axis = axis if axis is not None else _RING_AXIS
    if mesh is None or mesh.shape.get(axis, 1) == 1:
        from .attention import dot_product_attention, make_causal_mask
        mask = make_causal_mask(q.shape[1])[None, None, :, :]
        return dot_product_attention(q, k, v, mask)

    axis_size = mesh.shape[axis]
    B, T, H, D = q.shape
    if T % axis_size:
        raise ValueError(f"seq len {T} not divisible by {axis}={axis_size}")
    t_local = T // axis_size

    spec = P(None, axis, None, None)

    def body(q_, k_, v_):
        return _ring_body(q_, k_, v_, axis=axis, axis_size=axis_size,
                          t_local=t_local)

    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)
    return fn(q, k, v)
