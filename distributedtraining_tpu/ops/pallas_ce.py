"""Pallas fused linear-cross-entropy for TPU: loss AND grads without ever
materializing the [N, V] logits.

The workload this accelerates is the reference's hot loop — HF-style
shifted CE over a 50k vocabulary every miner step
(hivetrain/training_manager.py:380-392). The standard XLA path writes the
f32 [B, T, V] logits to HBM (GPT-2-124M at B8/T1024: ~1.6 GB) and
traverses them several times across loss + backward; docs/perf.md names
this the step's #1 non-matmul HBM cost. The lax.scan variant in
ops/losses.py already avoids the buffer but pays an extra head-matmul
recompute *and* loses MXU efficiency to scan/checkpoint overhead
(measured 0.93x at 124M).

This module is the Pallas spelling, flash-attention's trick applied to
the vocab axis:

- forward: one (rows x vocab-tiles) grid keeping a running online-softmax
  (max, sumexp, label-logit) in VMEM; per-token loss plus the (m, s)
  stats come out, the logits never leave registers/VMEM.
- backward: two kernels, exactly like the library flash-attention split
  (dq vs dk/dv): a row-major kernel recomputes each logits tile, forms
  dz = (softmax - onehot) * g in-register and accumulates dh = dz @ W in
  VMEM; a vocab-major kernel does the same recompute and accumulates
  dW = dz^T @ h per vocab tile in f32.

FLOP accounting vs the standard path: +1 head-matmul equivalent in the
backward (the recompute, amortized across both kernels) in exchange for
~all the logits HBM traffic. At 124M the head matmul is ~27% of step
FLOPs, so the trade is near break-even on a single chip and improves
with model size (head share shrinks) and vocab (traffic grows) — the
measured A/B lives in bench.py / docs/perf.md.

Stats/labels ride (rows, 128)-lane buffers (value broadcast across
lanes), the same layout the library flash kernel uses for its l/m stats
— narrow 1-lane blocks are the classic Mosaic lowering trap.
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30   # -inf stand-in without nan hazards (python float: a jnp
               # scalar here would be a captured constant inside the kernels)
_LANES = 128                # stat-vector lane padding (Mosaic-safe blocks)


def _interpret() -> bool:
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:
        return True


def pallas_ce_available(hidden: jax.Array, head_kernel: jax.Array) -> bool:
    """True when the kernel path is expected to lower well: a real TPU
    backend and a lane-aligned embedding dim. Anything else routes to the
    lax.scan fallback in ops/losses.py."""
    return (not _interpret()) and hidden.shape[-1] % _LANES == 0


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(h_ref, w_ref, y_ref, loss_ref, m_ref, s_ref, ll_ref, *, v_real):
    """Grid (n_tiles, v_tiles), vocab innermost: the (m, s, label-logit)
    running stats live in the revisited output blocks / scratch and are
    finalized into per-token loss on the last vocab tile."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        s_ref[:] = jnp.zeros_like(s_ref)
        ll_ref[:] = jnp.zeros_like(ll_ref)

    z = jax.lax.dot_general(h_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    bv = z.shape[1]
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    z = jnp.where(col < v_real, z, _NEG)

    m_old = m_ref[:, :1]
    m_new = jnp.maximum(m_old, jnp.max(z, axis=1, keepdims=True))
    s_new = (s_ref[:, :1] * jnp.exp(m_old - m_new)
             + jnp.sum(jnp.exp(z - m_new), axis=1, keepdims=True))
    y = y_ref[:, :1]
    ll_new = ll_ref[:, :1] + jnp.sum(
        jnp.where(col == y, z, 0.0), axis=1, keepdims=True)

    lanes = m_ref.shape[1]
    m_ref[:] = jnp.broadcast_to(m_new, (m_new.shape[0], lanes))
    s_ref[:] = jnp.broadcast_to(s_new, (s_new.shape[0], lanes))
    ll_ref[:] = jnp.broadcast_to(ll_new, (ll_new.shape[0], lanes))

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        loss_ref[:] = m_ref[:] + jnp.log(s_ref[:]) - ll_ref[:]


def _dz_tile(h_ref, w_ref, y_ref, m_ref, s_ref, g_ref, j_v, *, v_real):
    """Recompute one logits tile and form dz = (softmax - onehot) * g.
    Shared by both backward kernels; returns dz in the compute dtype so
    the following matmul runs at full MXU rate."""
    z = jax.lax.dot_general(h_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    bv = z.shape[1]
    col = j_v * bv + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    p = jnp.where(col < v_real,
                  jnp.exp(z - m_ref[:, :1]) / s_ref[:, :1], 0.0)
    onehot = (col == y_ref[:, :1]).astype(jnp.float32)
    return ((p - onehot) * g_ref[:, :1]).astype(h_ref.dtype)


def _dh_kernel(h_ref, w_ref, y_ref, m_ref, s_ref, g_ref, dh_ref, acc, *,
               v_real):
    """Grid (n_tiles, v_tiles), vocab innermost: dh accumulates in an f32
    VMEM scratch across vocab tiles, written once per row tile."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    dz = _dz_tile(h_ref, w_ref, y_ref, m_ref, s_ref, g_ref, j, v_real=v_real)
    acc[:] += jax.lax.dot_general(dz, w_ref[:], (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        dh_ref[:] = acc[:].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, y_ref, m_ref, s_ref, g_ref, dw_ref, acc, *,
               v_real):
    """Grid (v_tiles, n_tiles), rows innermost: dW accumulates per vocab
    tile in f32 VMEM, written once per vocab tile (padded-row tokens
    arrive with g = 0 so they contribute nothing)."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    j = pl.program_id(0)
    dz = _dz_tile(h_ref, w_ref, y_ref, m_ref, s_ref, g_ref, j, v_real=v_real)
    acc[:] += jax.lax.dot_general(dz, h_ref[:], (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(1) - 1)
    def _():
        dw_ref[:] = acc[:]


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _stat_spec(bn):
    return pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0))


def _fwd_call(h, w, y2, *, bn, bv, v_real, interpret):
    n, e = h.shape
    vp = w.shape[0]
    grid = (n // bn, vp // bv)
    out = jax.ShapeDtypeStruct((n, _LANES), jnp.float32)
    kernel = functools.partial(_fwd_kernel, v_real=v_real)
    loss, m, s = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, e), lambda i, j: (j, 0)),
            _stat_spec(bn),
        ],
        out_specs=[_stat_spec(bn), _stat_spec(bn), _stat_spec(bn)],
        out_shape=[out, out, out],
        scratch_shapes=[pltpu.VMEM((bn, _LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(h, w, y2)
    return loss, m, s


def _bwd_calls(h, w, y2, m, s, g2, *, bn, bv, v_real, interpret):
    n, e = h.shape
    vp = w.shape[0]
    stat = _stat_spec(bn)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, v_real=v_real),
        grid=(n // bn, vp // bv),
        in_specs=[
            pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, e), lambda i, j: (j, 0)),
            stat, stat, stat, stat,
        ],
        out_specs=pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, e), h.dtype),
        scratch_shapes=[pltpu.VMEM((bn, e), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(h, w, y2, m, s, g2)

    # vocab-major: same tile recompute, dW side (note the swapped grid —
    # index maps address (row_tile, vocab_tile) as (grid1, grid0))
    stat_sw = pl.BlockSpec((bn, _LANES), lambda j, i: (i, 0))
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, v_real=v_real),
        grid=(vp // bv, n // bn),
        in_specs=[
            pl.BlockSpec((bn, e), lambda j, i: (i, 0)),
            pl.BlockSpec((bv, e), lambda j, i: (j, 0)),
            stat_sw, stat_sw, stat_sw, stat_sw,
        ],
        out_specs=pl.BlockSpec((bv, e), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((vp, e), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bv, e), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(h, w, y2, m, s, g2)
    return dh, dw


# ---------------------------------------------------------------------------
# custom_vjp + public wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _per_token_ce(bn, bv, v_real, interpret, h, w, y2):
    loss, _, _ = _fwd_call(h, w, y2, bn=bn, bv=bv, v_real=v_real,
                           interpret=interpret)
    return loss[:, 0]


def _per_token_ce_fwd(bn, bv, v_real, interpret, h, w, y2):
    loss, m, s = _fwd_call(h, w, y2, bn=bn, bv=bv, v_real=v_real,
                           interpret=interpret)
    return loss[:, 0], (h, w, y2, m, s)


def _per_token_ce_bwd(bn, bv, v_real, interpret, res, g):
    h, w, y2, m, s = res
    g2 = jnp.broadcast_to(g.astype(jnp.float32)[:, None],
                          (g.shape[0], _LANES))
    dh, dw = _bwd_calls(h, w, y2, m, s, g2, bn=bn, bv=bv, v_real=v_real,
                        interpret=interpret)
    return dh, dw.astype(w.dtype), np.zeros(y2.shape, jax.dtypes.float0)


_per_token_ce.defvjp(_per_token_ce_fwd, _per_token_ce_bwd)


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _env_block(env: str, default: int, mult: int, why: str) -> int:
    raw = os.environ.get(env)
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{env}={raw!r} is not an integer") from None
    if val <= 0 or val % mult:
        raise ValueError(f"{env}={val} must be a positive multiple "
                         f"of {mult} ({why})")
    return val


def _resolve_blocks(block_n: int, block_v: int) -> tuple[int, int]:
    """On-chip tuning knobs without an edit-redeploy loop (the rig's TPU
    access is intermittent; see scripts/measure.sh). Defaults are the
    VMEM-budgeted analysis values in the module docstring. Validated
    eagerly: a bad value must fail with a named error, not burn a
    TPU-access window on a cryptic Mosaic lowering failure.

    NOTE: read at TRACE time — they bind at the first compile of a given
    jitted program; changing them in-process later does not retrace
    (bn/bv are not part of the program's avals). Set them before the
    first step, or construct a fresh engine per setting (the tuning
    sweep in bench.py does the latter).

    BN is a sublane dim (16 covers the strictest bf16 tiling); BV is the
    MINORMOST dim of the logits tiles — sub-128 lanes are the narrow-lane
    Mosaic trap the module docstring warns about."""
    return (_env_block("DT_PALLAS_CE_BN", block_n, 16, "sublane tiling"),
            _env_block("DT_PALLAS_CE_BV", block_v, 128, "lane width"))


def fused_ce_loss(hidden: jax.Array, head_kernel: jax.Array,
                  labels: jax.Array,
                  loss_mask: Optional[jax.Array] = None,
                  *, block_n: int = 1024, block_v: int = 512,
                  interpret: Optional[bool] = None
                  ) -> tuple[jax.Array, jax.Array]:
    """Drop-in for ops.losses.fused_linear_cross_entropy, Pallas path.

    hidden: [..., E] activations ALREADY shifted/aligned to ``labels``
    [...]; head_kernel: [V, E]; loss_mask like labels. Returns
    (mean_loss, token_count) — the causal_lm_loss contract. Differentiable
    w.r.t. hidden and head_kernel (custom_vjp, two backward kernels).
    """
    if interpret is None:
        interpret = _interpret()
        if interpret:
            # an explicit impl='pallas' (or a direct call) off-TPU would
            # otherwise silently run the kernels in interpret mode —
            # orders of magnitude slower than the scan fallback the
            # caller thinks they opted out of
            warnings.warn(
                "pallas fused-CE requested on a non-TPU backend; running "
                "in Pallas INTERPRET mode (very slow). Use "
                "fused_loss=True/'scan' off-TPU, or pass interpret=True "
                "explicitly to silence this.", stacklevel=2)
    block_n, block_v = _resolve_blocks(block_n, block_v)
    total, count = _fused_ce_totals(hidden, head_kernel, labels, loss_mask,
                                    block_n=block_n, block_v=block_v,
                                    interpret=interpret)
    return total / jnp.maximum(count, 1.0), jnp.maximum(count, 1.0)


def _fused_ce_totals(hidden: jax.Array, head_kernel: jax.Array,
                     labels: jax.Array,
                     loss_mask: Optional[jax.Array],
                     *, block_n: int, block_v: int,
                     interpret: bool) -> tuple[jax.Array, jax.Array]:
    """(sum of masked per-token losses, RAW mask sum) — the un-normalized
    half of ``fused_ce_loss``, split out so the shard_map spelling can
    psum totals across devices before normalizing (a per-shard
    ``max(count, 1)`` clamp would silently inflate the denominator for
    shards whose rows are all padding)."""
    e = hidden.shape[-1]
    v = head_kernel.shape[0]
    h = hidden.reshape(-1, e)
    y = labels.reshape(-1).astype(jnp.int32)
    n = h.shape[0]

    bn = min(block_n, _round_up(n, 16))
    bv = min(block_v, _round_up(v, _LANES))
    n_pad = _round_up(n, bn)
    v_pad = _round_up(v, bv)
    if n_pad > n:
        h = jnp.pad(h, ((0, n_pad - n), (0, 0)))
        y = jnp.pad(y, (0, n_pad - n))
    w = head_kernel
    if v_pad > v:
        w = jnp.pad(w, ((0, v_pad - v), (0, 0)))
    # the kernel compares label lanes against vocab columns; broadcast to
    # the stat-lane layout once here (4 bytes/token/lane, trivial next to
    # the saved logits)
    y2 = jnp.broadcast_to(y[:, None], (n_pad, _LANES))

    per_tok = _per_token_ce(bn, bv, v, interpret, h, w, y2)[:n]
    per_tok = per_tok.reshape(labels.shape)
    if loss_mask is not None:
        msk = loss_mask.astype(per_tok.dtype)
    else:
        msk = jnp.ones_like(per_tok)
    return jnp.sum(per_tok * msk), jnp.sum(msk)


# ---------------------------------------------------------------------------
# mesh spelling: the same kernels under shard_map
# ---------------------------------------------------------------------------

def fused_ce_loss_sharded(hidden: jax.Array, head_kernel: jax.Array,
                          labels: jax.Array,
                          loss_mask: Optional[jax.Array] = None,
                          *, mesh, block_n: int = 1024, block_v: int = 512,
                          interpret: Optional[bool] = None,
                          inner: str = "pallas"
                          ) -> tuple[jax.Array, jax.Array]:
    """``fused_ce_loss`` on a dp/fsdp/tp mesh (shard_map over the Pallas
    kernels — pallas_call is not auto-partitionable under GSPMD, which is
    why the plain spelling is single-device).

    ``inner`` selects the per-device tile engine: "pallas" (the Mosaic
    kernels, TPU) or "scan" (losses._scan_ce_totals — portable lax with
    the identical collective structure). The shard_map wrapper is the
    same either way: inside it XLA sees LOCAL shapes, so the vocab
    tiling survives partitioning at any scale — left to GSPMD, the
    plain scan spelling re-materializes full-vocab buffers at 8B
    (measured, scripts/scale_aot.py).

    Layout (parallel/sharding.py rules): hidden [B, T, E] rides the batch
    sharding P(('dp','fsdp'), None, None); the head [V, E] is a param
    sharded P('tp', 'fsdp'). Per device: the head shard is all-gathered
    (the SAME traffic GSPMD inserts for the materialized-logits matmul
    against these shardings), the device's rows are then split across tp
    as well — every device computes a DISTINCT row chunk against the full
    vocabulary, so tp scales the kernel instead of duplicating it — and
    the masked totals psum across the whole mesh. Reverse-mode AD of the
    shard_map transposes the all-gathers into psum_scatters, landing dW
    shards exactly where the optimizer expects them.

    sp meshes compose too: the engine shifts labels GLOBALLY before
    sharding (labels-carry-the-shift, engine/train.py), so each sequence
    shard is self-contained and the kernel never reads across a
    sequence-shard boundary; the sp axis simply joins the row split.
    """
    from jax.sharding import PartitionSpec as P
    try:  # moved in newer jax
        from jax.experimental.shard_map import shard_map
    except ImportError:  # pragma: no cover
        from jax.shard_map import shard_map

    if inner not in ("pallas", "scan"):
        raise ValueError(f"unknown inner tile engine {inner!r}")
    if inner == "pallas" and interpret is None:
        interpret = _interpret()
        if interpret:
            warnings.warn(
                "pallas fused-CE (sharded) requested on a non-TPU backend; "
                "running in Pallas INTERPRET mode (very slow). Use "
                "fused_loss=True/'scan' off-TPU, or pass interpret=True "
                "explicitly to silence this.", stacklevel=2)
    block_n, block_v = _resolve_blocks(block_n, block_v)

    names = mesh.axis_names
    row_axes = tuple(a for a in ("dp", "fsdp") if a in names)
    sp_ax = "sp" if "sp" in names else None
    tp_ax = "tp" if "tp" in names else None
    fsdp_ax = "fsdp" if "fsdp" in names else None
    tp = int(mesh.shape[tp_ax]) if tp_ax else 1
    psum_axes = (row_axes + ((sp_ax,) if sp_ax else ())
                 + ((tp_ax,) if tp_ax else ()))

    if loss_mask is None:
        loss_mask = jnp.ones(labels.shape, jnp.float32)

    def local(h, w, y, m):
        # reassemble the full head from its tp x fsdp shards
        if fsdp_ax is not None:
            w = jax.lax.all_gather(w, fsdp_ax, axis=1, tiled=True)
        if tp_ax is not None:
            w = jax.lax.all_gather(w, tp_ax, axis=0, tiled=True)
        e = h.shape[-1]
        h2 = h.reshape(-1, e)
        y2 = y.reshape(-1)
        m2 = m.reshape(-1)
        if tp > 1:
            # this device's slice of the local rows: tp peers hold the
            # same batch shard, so carving it up makes tp a second data
            # axis for the kernel (zero duplicate FLOPs). Padding rows
            # carry mask 0 and vanish from both totals; AD transposes the
            # pad back to a slice for dh.
            n = h2.shape[0]
            n_pad = _round_up(n, tp)
            if n_pad > n:
                h2 = jnp.pad(h2, ((0, n_pad - n), (0, 0)))
                y2 = jnp.pad(y2, (0, n_pad - n))
                m2 = jnp.pad(m2, (0, n_pad - n))
            per = n_pad // tp
            i = jax.lax.axis_index(tp_ax)
            h2 = jax.lax.dynamic_slice_in_dim(h2, i * per, per, 0)
            y2 = jax.lax.dynamic_slice_in_dim(y2, i * per, per, 0)
            m2 = jax.lax.dynamic_slice_in_dim(m2, i * per, per, 0)
        if inner == "scan":
            from .losses import _scan_ce_totals
            total, count = _scan_ce_totals(h2, w, y2, m2, chunk=block_v)
        else:
            total, count = _fused_ce_totals(h2, w, y2, m2, block_n=block_n,
                                            block_v=block_v,
                                            interpret=interpret)
        total = jax.lax.psum(total, psum_axes)
        count = jax.lax.psum(count, psum_axes)
        return total, count

    total, count = shard_map(
        local, mesh=mesh,
        # sequence axis rides sp (the engine's mesh spelling shifts the
        # LABELS, not the hidden states, so sequence shards carry no
        # cross-shard dependency — see _fused_lm_loss)
        in_specs=(P(row_axes or None, sp_ax, None),
                  P(tp_ax, fsdp_ax),
                  P(row_axes or None, sp_ax),
                  P(row_axes or None, sp_ax)),
        out_specs=(P(), P()),
        check_rep=False,
    )(hidden, head_kernel, labels, loss_mask)
    return total / jnp.maximum(count, 1.0), jnp.maximum(count, 1.0)
