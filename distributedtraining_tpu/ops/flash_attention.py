"""Pallas blockwise (flash) attention for TPU.

Online-softmax attention that never materializes the [T, T] score matrix in
HBM — the long-sequence path. Grid: (batch*heads, q_blocks); the kernel scans
kv blocks with running max/denominator in VMEM scratch.

``flash_attention`` returns None when it declines (non-TPU backend, unpadded
shapes, or unsupported masks) and the caller falls back to the dense XLA path
(ops/attention.py) — identical numerics, different memory profile.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    *,
                    attention_mask: Optional[jax.Array] = None,
                    segment_ids: Optional[jax.Array] = None,
                    block_q: int = 256, block_kv: int = 256
                    ) -> Optional[jax.Array]:
    """[B, T, H, D] causal flash attention. Returns None to decline."""
    B, T, H, D = q.shape
    if not _on_tpu():
        return None
    if attention_mask is not None or segment_ids is not None:
        # masked variants ride the dense path for now
        return None
    if T % block_q or T % block_kv or D % 128 and D not in (64,):
        return None
    try:
        from jax.experimental import pallas as pl
    except ImportError:
        return None

    orig_dtype = q.dtype
    scale = D ** -0.5
    nq = T // block_q

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qb = q_ref[...].astype(jnp.float32) * scale  # [block_q, D]

        def body(ki, carry):
            acc, m_prev, l_prev = carry
            kb = pl.load(k_ref, (pl.dslice(ki * block_kv, block_kv), slice(None)))
            vb = pl.load(v_ref, (pl.dslice(ki * block_kv, block_kv), slice(None)))
            s = qb @ kb.astype(jnp.float32).T  # [block_q, block_kv]
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + p @ vb.astype(jnp.float32)
            return acc, m_new, l_new

        acc0 = jnp.zeros((block_q, D), jnp.float32)
        m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q,), jnp.float32)
        # causal: kv blocks past the diagonal contribute nothing — skip them.
        # Last query position in this block is (qi+1)*block_q - 1, so the
        # number of kv blocks that intersect the causal triangle is
        # floor(last_pos / block_kv) + 1.
        num_kv = ((qi + 1) * block_q - 1) // block_kv + 1
        acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))
        o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)

    # fold batch and heads into the grid's first axis
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    try:
        out = pl.pallas_call(
            kernel,
            grid=(B * H, nq),
            in_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((B * H, T, D), orig_dtype),
        )(qt, kt, vt)
    except Exception:
        return None  # kernel unsupported on this backend/version — dense fallback
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
