"""Flash (blockwise-softmax) attention for TPU.

Online-softmax attention that never materializes the [T, T] score matrix in
HBM. The implementation rides JAX's Pallas TPU ops library
(``jax.experimental.pallas.ops.tpu.flash_attention``), which provides the
forward *and* backward kernels behind a ``custom_vjp`` — differentiability
is what makes this usable in the train step, where a forward-only kernel
would silently fall back to dense under ``jax.grad`` (Pallas has no
autodiff).

Supports causal masking and packed-sequence ``segment_ids`` (block-diagonal
attention), which is the data pipeline's hot path. ``flash_attention``
returns None when it declines (non-TPU backend, unsupported shapes, explicit
padding masks) and the caller falls back to the dense XLA path
(ops/attention.py) — identical numerics, different memory profile.

Layouts: this framework uses [B, T, H, D]; the kernel wants [B, H, T, D].
The transposes are free at trace level (XLA fuses them into the kernel's
block loads).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


@functools.cache
def _kernel():
    """The library entry points, or None when unavailable."""
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as fa
        return fa
    except ImportError:
        return None


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    *,
                    attention_mask: Optional[jax.Array] = None,
                    segment_ids: Optional[jax.Array] = None,
                    kv_segment_ids: Optional[jax.Array] = None
                    ) -> Optional[jax.Array]:
    """[B, T, H, D] causal flash attention. Returns None to decline.

    segment_ids: [B, T] packing ids (block-diagonal attention), as produced
    by data/packing.py. attention_mask (padding) declines — eval batches with
    ragged padding ride the dense path.
    """
    B, T, H, D = q.shape
    if not _on_tpu():
        return None
    if attention_mask is not None:
        return None
    fa = _kernel()
    if fa is None:
        return None
    # kernel block minimums: short sequences gain nothing from blocking
    if T < 256 or T % 128:
        return None
    # head dims outside the kernel's lane tiling would fail in Mosaic
    # lowering at jit-compile time — beyond this function's try/except reach
    if D % 128 and D != 64:
        return None

    seg = None
    if segment_ids is not None:
        kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
        seg = fa.SegmentIds(q=segment_ids.astype(jnp.int32),
                            kv=kv_seg.astype(jnp.int32))

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    try:
        out = fa.flash_attention(qt, kt, vt, segment_ids=seg, causal=True,
                                 sm_scale=D ** -0.5,
                                 block_sizes=_block_sizes(fa, T))
    except Exception:
        return None  # unsupported shape/backend — dense fallback
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _block_sizes(fa, T: int):
    """Measured on v5e (GPT-2 heads, D=64): the library default of 128-wide
    blocks leaves >2x on the table; a whole-row query block with 256-wide kv
    blocks is the fastest fwd+bwd schedule at T<=2048 and stays VMEM-safe at
    longer T via the 1024 cap."""
    bq = next(b for b in (1024, 512, 256, 128) if T % b == 0)
    bk = 256 if T % 256 == 0 else 128
    return fa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)
