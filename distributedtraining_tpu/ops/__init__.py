"""Hot-path ops: attention (dense / flash-pallas / ring), losses.

These are the MXU-bound inner loops; everything is shaped for XLA fusion
(static shapes, fp32 softmax accumulation over bf16 operands).
"""

from .attention import causal_attention, make_causal_mask
from .losses import causal_lm_loss, cross_entropy_with_logits

__all__ = [
    "causal_attention",
    "make_causal_mask",
    "causal_lm_loss",
    "cross_entropy_with_logits",
]
