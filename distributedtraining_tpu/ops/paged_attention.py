"""Fused paged-attention decode kernel for TPU.

The serving plane's per-token cost: every decode step attends one fresh
query per sequence over that sequence's paged KV context. The XLA
spelling (engine/serve.py before this kernel) gathered every slot's full
padded context out of the page pool into a dense ``[B, S, Hkv, D]``
tensor per layer per token — O(B*S) HBM bytes moved to compute an
output whose useful work is O(sum(seq_lens)) — and then materialized a
``[B, Tq, S+Tq]`` boolean mask on top. This module deletes both: one
Pallas kernel walks each slot's page table, DMAs exactly the pages the
table names from HBM into VMEM, runs the fp32 online-softmax attend
in-kernel (GQA-aware: pages hold ``Hkv`` heads, queries ``Hq``; no
``jnp.repeat`` broadcast ever materializes), folds the step's OWN fresh
(k, v) in as the final context column (they are not in the pool yet —
the engine scatters them after the forward), and masks dead page slots
with ``seq_lens``.

Why not ``jax.experimental.pallas.ops.tpu.paged_attention``: the
library kernel downcasts every loaded K/V block to bfloat16 before the
QK/PV matmuls (``MultiPageAsyncCopyDescriptor._maybe_dequantize``),
which breaks this repo's greedy-parity contract (engine outputs pinned
token-identical to the full-recompute oracle at f32 — docs/serving.md).
This kernel keeps the pool dtype through the loads and accumulates in
fp32, so parity vs ``ops.attention.cached_attention`` holds to 1e-6.

Structure follows ops/flash_attention.py's discipline: capability probe
-> kernel -> XLA fallback (:func:`paged_decode_reference`, which IS the
pre-kernel math, so CPU tier-1 stays bit-identical), plus explicit
``interpret=`` plumbing so the kernel's numerics are pinned on CPU in
tier-1 and on real hardware in tests_tpu/.

Layouts: q / k_new / v_new are ``[B, 1, H(kv), D]`` (decode is one
token per slot per step); the page pool is one layer's
``[pages, P, Hkv, D]`` slice; ``page_tables`` is ``[B, MP]`` int32 into
the pool (padded rows point at trash page 0); ``seq_lens`` ``[B]`` is
each slot's REAL context length (the fresh token sits at position
``seq_lens[b]``, always visible to itself).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .attention import NEG_INF, cached_attention

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover — pallas-less backend
    pl = None
    pltpu = None

# one decode chunk = this many pages DMA'd + attended per grid step;
# the (slot, page) buckets ride a power-of-two ladder (engine/serve.py
# BucketLadder), so any larger MP is divisible and smaller MPs run as
# a single chunk
PAGES_PER_CHUNK = 8


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _chunk_pages(mp: int) -> int:
    """Largest power-of-two divisor of ``mp`` capped at PAGES_PER_CHUNK."""
    c = 1
    while c < PAGES_PER_CHUNK and mp % (c * 2) == 0:
        c *= 2
    return c


def _online_update(s, v, valid, acc_ref, m_ref, l_ref, *,
                   new_token: bool = False):
    """Streaming-softmax accumulate: ``acc`` holds the UNNORMALIZED
    weighted sum (the division by ``l`` happens once, at finalize),
    ``m``/``l`` the running max / normalizer per (kv head, group row).
    ``p`` is re-zeroed under the mask so a fully-dead chunk contributes
    exact zeros — the blockwise_attention convention."""
    m_prev = m_ref[...][..., :1]                         # [Hkv, G, 1]
    l_prev = l_ref[...][..., :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    p = jnp.where(valid, p, 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if new_token:
        # p [Hkv, G, 1] x v [Hkv, 1, D] -> outer product per kv head
        pv = p * v
    else:
        # pv[h, g, d] = sum_t p[h, g, t] * v[t, h, d]
        pv = jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)


def _decode_kernel(page_tables_ref, seq_lens_ref,   # scalar prefetch
                   q_ref, k_pages_ref, v_pages_ref, k_new_ref, v_new_ref,
                   o_ref,
                   k_buf, v_buf, acc_ref, m_ref, l_ref, sem,
                   *, pages_per_chunk: int, page_size: int,
                   n_chunks: int, n_kv_heads: int, group: int,
                   scale: float):
    """One (batch row, context chunk) grid step of the fused decode.

    Grid is ``(B, n_chunks + 1)``: the first ``n_chunks`` steps DMA
    ``pages_per_chunk`` pages of this row's table and fold them into
    the running online softmax (f32 ``m``/``l``/unnormalized ``acc``
    persist in VMEM scratch across the sequential grid); the FINAL step
    appends the fresh (k_new, v_new) column — the token being decoded,
    not yet in the pool — and writes ``acc / l``. Chunks wholly past
    ``seq_lens[b]`` skip both the DMA and the math (the bucket-padded
    tail of a short sequence costs nothing but the grid iteration).
    """
    b = pl.program_id(0)
    i = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    chunk = pages_per_chunk * page_size

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale             # [Hq, D]
    qg = q.reshape(n_kv_heads, group, q.shape[-1])       # [Hkv, G, D]

    @pl.when(jnp.logical_and(i < n_chunks, i * chunk < seq_len))
    def _context_chunk():
        # gather exactly the pages the table names for this chunk
        for j in range(pages_per_chunk):
            page = page_tables_ref[b, i * pages_per_chunk + j]
            pltpu.make_async_copy(
                k_pages_ref.at[page], k_buf.at[j], sem.at[0]).start()
            pltpu.make_async_copy(
                v_pages_ref.at[page], v_buf.at[j], sem.at[1]).start()
        for j in range(pages_per_chunk):
            pltpu.make_async_copy(
                k_pages_ref.at[0], k_buf.at[j], sem.at[0]).wait()
            pltpu.make_async_copy(
                v_pages_ref.at[0], v_buf.at[j], sem.at[1]).wait()
        k = k_buf[...].astype(jnp.float32).reshape(chunk, n_kv_heads, -1)
        v = v_buf[...].astype(jnp.float32).reshape(chunk, n_kv_heads, -1)
        # s[h, g, t] = q[h, g, :] . k[t, h, :]
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # [Hkv, G, T]
        pos = i * chunk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = pos < seq_len                            # dead pages masked
        s = jnp.where(valid, s, NEG_INF)
        _online_update(s, v, valid, acc_ref, m_ref, l_ref)

    @pl.when(i == n_chunks)
    def _append_fresh_and_finalize():
        kn = k_new_ref[0].astype(jnp.float32)            # [Hkv, D]
        vn = v_new_ref[0].astype(jnp.float32)
        s = jnp.einsum("hgd,hd->hg", qg, kn,
                       preferred_element_type=jnp.float32)[..., None]
        valid = jnp.ones(s.shape, dtype=jnp.bool_)
        _online_update(s, vn[:, None, :], valid, acc_ref, m_ref, l_ref,
                       new_token=True)
        l = l_ref[...][..., :1]                          # l >= exp(0) > 0
        o = acc_ref[...] / l
        o_ref[0] = o.reshape(o_ref.shape[1:]).astype(o_ref.dtype)


def _build_call(B, Hq, Hkv, D, P, MP, q_dtype, page_dtype,
                interpret: bool):
    """Construct the pallas_call for one shape signature."""
    group = Hq // Hkv
    ppc = _chunk_pages(MP)
    n_chunks = MP // ppc
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # page_tables, seq_lens
        grid=(B, n_chunks + 1),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, i, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),     # k_pages (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),     # v_pages (HBM)
            pl.BlockSpec((1, Hkv, D), lambda b, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, Hkv, D), lambda b, i, *_: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, i, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((ppc, P, Hkv, D), page_dtype),     # k chunk
            pltpu.VMEM((ppc, P, Hkv, D), page_dtype),     # v chunk
            pltpu.VMEM((Hkv, group, D), jnp.float32),     # acc
            pltpu.VMEM((Hkv, group, 128), jnp.float32),   # running max
            pltpu.VMEM((Hkv, group, 128), jnp.float32),   # running sum
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, pages_per_chunk=ppc, page_size=P,
        n_chunks=n_chunks, n_kv_heads=Hkv, group=group,
        scale=D ** -0.5)
    return pl.pallas_call(  # devprof: exempt (attributed under serve.decode in-step; standalone A/Bs wrap it as serve.decode_attn in bench._time_decode_attn_kernel)
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q_dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )


@functools.cache
def _probe_ok() -> bool:
    """One-time capability probe: compile+run the kernel EAGERLY at a
    tiny representative shape on this backend. A Mosaic lowering failure
    inside a caller's jit would surface at the OUTER compile — past any
    try/except around the traced call (the flash_attention caveat) — so
    the decision to use the kernel at all is made here, once, where the
    failure is catchable. False = decline forever, XLA fallback."""
    if pl is None or not _on_tpu():
        return False
    try:
        B, Hq, Hkv, D, P, MP = 1, 2, 1, 64, 8, 1
        z = jnp.zeros((B, 1, Hq, D), jnp.float32)
        zp = jnp.zeros((3, P, Hkv, D), jnp.float32)
        zn = jnp.zeros((B, 1, Hkv, D), jnp.float32)
        call = _build_call(B, Hq, Hkv, D, P, MP, z.dtype, zp.dtype, False)
        out = call(jnp.ones((B, MP), jnp.int32), jnp.ones((B,), jnp.int32),
                   z[:, 0], zp, zp, zn[:, 0], zn[:, 0])
        jax.block_until_ready(out)
        return True
    except Exception:  # pragma: no cover — hardware-dependent
        return False


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array, k_new: jax.Array,
                           v_new: jax.Array, *,
                           interpret: bool | None = None
                           ) -> Optional[jax.Array]:
    """The fused kernel, or None to decline (caller falls back).

    q/k_new/v_new: ``[B, 1, Hq/Hkv/Hkv, D]``; k_pages/v_pages: one
    layer's ``[pages, P, Hkv, D]`` pool; page_tables ``[B, MP]`` int32;
    seq_lens ``[B]`` int32. Returns ``[B, 1, Hq, D]``.

    ``interpret=None`` declines off-TPU (tier-1 CPU rides the XLA
    fallback); ``interpret=True`` forces the interpreter so the KERNEL
    math is pinned on CPU (tests, bench's degraded A/B).
    """
    if pl is None:
        return None
    if interpret is None:
        if not _probe_ok():
            return None
        interpret = False
    B, Tq, Hq, D = q.shape
    if Tq != 1:
        return None                  # decode is one token per step
    pool, P, Hkv, Dk = k_pages.shape
    if Dk != D or Hq % Hkv:
        return None
    MP = page_tables.shape[1]
    try:
        call = _build_call(B, Hq, Hkv, D, P, MP, q.dtype, k_pages.dtype,
                           interpret)
        out = call(page_tables.astype(jnp.int32),
                   seq_lens.astype(jnp.int32),
                   q[:, 0], k_pages, v_pages, k_new[:, 0], v_new[:, 0])
    except Exception:
        return None                  # unsupported shape/backend
    return out[:, None].astype(q.dtype)


def paged_decode_reference(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array, k_new: jax.Array,
                           v_new: jax.Array) -> jax.Array:
    """The XLA spelling the kernel replaces — gather the table's pages
    into a padded context, append the fresh column, broadcast GQA heads,
    and run :func:`ops.attention.cached_attention` (whose context-length
    mask is an iota compare fused into the scores, not a materialized
    boolean buffer). This is the production CPU path AND the parity
    oracle the kernel is pinned against."""
    B, Tq, Hq, D = q.shape
    pool, P, Hkv, _ = k_pages.shape
    MP = page_tables.shape[1]
    k_ctx = k_pages[page_tables].reshape(B, MP * P, Hkv, D)
    v_ctx = v_pages[page_tables].reshape(B, MP * P, Hkv, D)
    k_full = jnp.concatenate([k_ctx, k_new], axis=1)
    v_full = jnp.concatenate([v_ctx, v_new], axis=1)
    if Hkv != Hq:
        rep = Hq // Hkv
        k_full = jnp.repeat(k_full, rep, axis=2)
        v_full = jnp.repeat(v_full, rep, axis=2)
    return cached_attention(q, k_full, v_full, seq_lens)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_tables: jax.Array, seq_lens: jax.Array,
                    k_new: jax.Array, v_new: jax.Array) -> jax.Array:
    """Model-facing entry (gpt2/llama decode blocks): the kernel when
    the backend supports it, the XLA reference otherwise — identical
    numerics either way (parity pinned in tests/test_paged_attention.py
    and tests_tpu/test_paged_attention_tpu.py)."""
    out = paged_decode_attention(q, k_pages, v_pages, page_tables,
                                 seq_lens, k_new, v_new)
    if out is not None:
        return out
    return paged_decode_reference(q, k_pages, v_pages, page_tables,
                                  seq_lens, k_new, v_new)
