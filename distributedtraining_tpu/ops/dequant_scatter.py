"""Fused dequantize->scatter-add kernel for packed wire-v2 entries.

The averager-side hot loop of the v2 wire: folding one packed
contribution ``{"idx": int32[k], "q": int8|f32[k], "scale": f32}`` into
a running f32 aggregate is ``acc[idx] += w * q_f32 * scale`` — k useful
element updates against a buffer of n >> k elements. The XLA spelling
(``delta._accum_packed``: ``flat.at[idx].add(w * q * scale)``) is
functionally a full-buffer copy plus a scatter: without guaranteed
donation XLA rewrites every contribution as "copy n elements, then
touch k", so ingesting M contributions writes O(M*n) HBM bytes for
O(M*k) of work — the measured ``delta.accumulate`` cost the device
observatory attributes (docs/perf.md round 17). This kernel does the
dequantize (int8 -> f32 times the folded ``w*scale``) and the
scatter-add in ONE Pallas program whose accumulator is aliased in-place
(``input_output_aliases``), so bytes written per contribution drop to
O(k) and the dense intermediate of the densify-then-add spelling never
exists.

Same discipline as ops/flash_attention.py / ops/paged_attention.py:
one-time capability probe -> kernel -> XLA fallback, and explicit
``interpret=`` plumbing so tier-1 pins the kernel math on CPU. Leaves
whose flat size exceeds the VMEM budget (:data:`MAX_ACC_ELEMS`) ride
the XLA spelling — correctness identical (duplicate indices SUM in both,
the ``_accum_packed`` convention; the screened-upstream hostile cases
keep their semantics because this kernel is only reached AFTER
``packed_matches`` admission, like every accumulate path).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover — pallas-less backend
    pl = None
    pltpu = None

# accumulator leaves above this many f32 elements stay on the XLA path:
# the whole flat buffer must sit in VMEM next to the idx/q/val arrays
# (~8 MB of the ~16 MB/core budget)
MAX_ACC_ELEMS = 2 * 1024 * 1024

# test/bench hook: force the interpreter so CPU lanes exercise the
# KERNEL math instead of the XLA fallback (set via use_interpret)
_FORCE_INTERPRET = False


def use_interpret(on: bool) -> None:
    """Route :func:`enabled` callers through the interpreter (CPU test
    and bench lanes). Production never sets this."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = bool(on)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _scatter_kernel(acc_ref, idx_ref, q_ref, sw_ref, out_ref, val_ref):
    """acc[idx[j]] += q[j] * sw for j in [0, k). ``acc`` is aliased to
    ``out`` (true in-place: O(k) bytes written); the dequantize runs
    once, vectorized, into VMEM scratch; the scatter itself is a serial
    read-modify-write loop — duplicates SUM, deterministically."""
    del acc_ref  # aliased: out_ref IS the accumulator
    val_ref[...] = q_ref[...].astype(jnp.float32) * sw_ref[0]
    k = idx_ref.shape[0]

    def body(j, _):
        pos = idx_ref[j]
        out_ref[pl.ds(pos, 1)] = out_ref[pl.ds(pos, 1)] + val_ref[pl.ds(j, 1)]
        return 0

    jax.lax.fori_loop(0, k, body, 0)


def _build_call(n: int, k: int, q_dtype, interpret: bool):
    return pl.pallas_call(  # devprof: exempt (attributed under delta.dequant_scatter — the wrapped _accum_packed_kernel program this kernel runs inside)
        _scatter_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # acc
            pl.BlockSpec(memory_space=pltpu.VMEM),            # idx
            pl.BlockSpec(memory_space=pltpu.VMEM),            # q
            pl.BlockSpec(memory_space=pltpu.SMEM),            # sw
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k,), jnp.float32)],       # dequant val
        input_output_aliases={0: 0},
        interpret=interpret,
    )


@functools.cache
def _probe_ok() -> bool:
    """One-time eager probe at a tiny shape: Mosaic either lowers the
    dynamic-index RMW loop on this backend or the kernel is declined
    forever (the paged_attention probe discipline — a lowering failure
    inside a caller's jit would be uncatchable there)."""
    if pl is None or not _on_tpu():
        return False
    try:
        out = _build_call(256, 8, jnp.int8, False)(
            jnp.zeros((256,), jnp.float32),
            jnp.arange(8, dtype=jnp.int32),
            jnp.ones((8,), jnp.int8),
            jnp.ones((1,), jnp.float32))
        jax.block_until_ready(out)
        return True
    except Exception:  # pragma: no cover — hardware-dependent
        return False


def enabled() -> bool:
    """True when accumulate paths should route packed entries through
    the kernel (TPU with a passing probe, or the CPU interpreter when a
    test/bench lane forced it)."""
    if _FORCE_INTERPRET:
        return pl is not None
    return _probe_ok()


def dequant_scatter_add(flat: jax.Array, idx: jax.Array, q: jax.Array,
                        scale_w, *, interpret: bool | None = None
                        ) -> Optional[jax.Array]:
    """``flat.at[idx].add(q_f32 * scale_w)`` as one fused in-place
    kernel, or None to decline (caller uses the XLA spelling).

    ``flat`` f32 [n]; ``idx`` int32 [k]; ``q`` int8 or f32 [k];
    ``scale_w`` the pre-folded ``weight * scale`` scalar. Indexed-form
    entries only (dense-form k==n entries are a plain fused add XLA
    already handles well).
    """
    if pl is None:
        return None
    if interpret is None:
        if _FORCE_INTERPRET:
            interpret = True
        elif _probe_ok():
            interpret = False
        else:
            return None
    n, k = flat.shape[0], idx.shape[0]
    if k == 0 or n > MAX_ACC_ELEMS:
        return None
    try:
        call = _build_call(n, k, q.dtype, interpret)
        sw = jnp.asarray(scale_w, jnp.float32).reshape(1)
        return call(flat.astype(jnp.float32), idx.astype(jnp.int32), q, sw)
    except Exception:
        return None  # unsupported shape/backend — XLA fallback
