"""Loss functions for causal LM training/eval.

Reproduces the reference's HF-style ``labels=input_ids`` shifted
cross-entropy (hivetrain/training_manager.py:380-385,
hivetrain/validation_logic.py:86-91) as explicit jittable functions, with
fp32 log-softmax over bf16 logits and padding-aware token counting (the
reference masks pad via HF's internal -100 handling; here the mask is an
explicit argument).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token CE, fp32. logits [..., V], labels [...] int."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - label_logits


def causal_lm_loss(logits: jax.Array, input_ids: jax.Array,
                   loss_mask: Optional[jax.Array] = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Shifted next-token loss.

    logits: [B, T, V]; input_ids: [B, T]; loss_mask: [B, T] 1.0 where the
    *label* token is real (pad and cross-segment boundaries excluded by the
    data pipeline).

    Returns (mean_loss, token_count) — token_count lets callers aggregate
    exactly across shards/batches (sum(loss*count)/sum(count)).
    """
    shift_logits = logits[:, :-1, :]
    shift_labels = input_ids[:, 1:]
    per_tok = cross_entropy_with_logits(shift_logits, shift_labels)
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(per_tok.dtype)
    else:
        m = jnp.ones_like(per_tok)
    total = jnp.sum(per_tok * m)
    count = jnp.maximum(jnp.sum(m), 1.0)
    return total / count, count


def perplexity(mean_loss: jax.Array) -> jax.Array:
    """The validator's second metric (hivetrain/validation_logic.py:93-97)."""
    return jnp.exp(mean_loss)


def fused_linear_cross_entropy(hidden: jax.Array, head_kernel: jax.Array,
                               labels: jax.Array,
                               loss_mask: Optional[jax.Array] = None,
                               *, chunk: int = 4096, impl: str = "auto",
                               interpret: Optional[bool] = None,
                               mesh=None
                               ) -> tuple[jax.Array, jax.Array]:
    """Shifted-label CE of ``logits = hidden @ head_kernel.T`` WITHOUT ever
    materializing the [N, V] logits tensor.

    The standard path materializes f32 logits (GPT-2-124M at B8/T1024:
    ~1.6 GB per traversal, several traversals per step — the single largest
    non-matmul HBM cost, docs/perf.md). Two spellings of the fix:

    - ``impl="pallas"`` (ops/pallas_ce.py): hand-written forward/backward
      kernels with the logits tiles living in VMEM only — the preferred
      path on TPU.
    - ``impl="scan"``: vocabulary scanned in ``chunk``-column tiles with a
      running (max, sumexp, label-logit) online softmax — the same trick
      flash attention plays on the sequence axis, applied to the vocab
      axis — the backward recomputing each tile via jax.checkpoint.
      Portable (any backend), but pays scan/checkpoint overhead.

    ``impl="auto"`` picks pallas when the backend/shape supports it, else
    scan. hidden: [..., E] activations ALREADY shifted/aligned to
    ``labels`` [...]; head_kernel: [V, E] (the tied wte); loss_mask like
    labels. Returns (mean_loss, token_count), the causal_lm_loss contract.
    """
    if impl == "auto":
        from .pallas_ce import pallas_ce_available
        impl = "pallas" if pallas_ce_available(hidden, head_kernel) else "scan"
    if impl not in ("pallas", "scan"):
        raise ValueError(f"unknown fused-CE impl {impl!r}")
    if impl == "scan" and interpret is not None:
        # interpret is a Pallas-only knob; silently dropping it would let
        # an off-TPU cross-check (impl left at "auto" -> scan) compare
        # the scan path against itself and prove nothing — same guard on
        # both the mesh and single-device routes
        raise ValueError("interpret= applies only to impl='pallas'; "
                         f"this call resolved to impl={impl!r}")
    if mesh is not None:
        # EVERY mesh run goes through the shard_map wrapper: local shapes
        # keep the vocab tiling intact under partitioning (GSPMD undoes
        # the plain scan's tiling at scale — full-vocab [N, V] buffers
        # measured at 8B, scripts/scale_aot.py) and the collectives are
        # explicit. ``inner`` picks pallas kernels (TPU) or the lax scan.
        from .pallas_ce import fused_ce_loss_sharded
        return fused_ce_loss_sharded(hidden, head_kernel, labels,
                                     loss_mask, mesh=mesh,
                                     interpret=interpret, inner=impl)
    if impl == "pallas":
        # ``interpret=True`` acknowledges a deliberate off-TPU run (numeric
        # cross-checks); None lets the kernel resolve the backend and warn
        # if that lands it in interpret mode
        from .pallas_ce import fused_ce_loss
        return fused_ce_loss(hidden, head_kernel, labels, loss_mask,
                             interpret=interpret)
    h = hidden.reshape(-1, hidden.shape[-1])
    y = labels.reshape(-1)
    m = (jnp.ones_like(y, jnp.float32) if loss_mask is None
         else loss_mask.reshape(-1))
    total, count = _scan_ce_totals(h, head_kernel, y, m, chunk=chunk)
    count = jnp.maximum(count, 1.0)
    return total / count, count


def _scan_ce_totals(h: jax.Array, w: jax.Array, y: jax.Array,
                    m: jax.Array, *, chunk: int = 4096
                    ) -> tuple[jax.Array, jax.Array]:
    """(masked total CE, masked token count) of ``h @ w.T`` vs ``y`` by
    the vocab-tiled online softmax — the lax.scan twin of
    pallas_ce._fused_ce_totals, shaped for shard_map bodies: everything
    here is LOCAL (no collectives; the caller psums). h: [N, E], w:
    [V, E] (already gathered), y/m: [N]. Inside shard_map the shapes XLA
    sees are per-device, so GSPMD cannot undo the tiling the way it does
    when this scan is left to the partitioner at 8B scale (the round-5
    SCALE artifact measured full-vocab [N, V] buffers materializing)."""
    E = h.shape[-1]
    V = w.shape[0]
    n_chunks = -(-V // chunk)
    v_pad = n_chunks * chunk
    wt = w
    if v_pad > V:
        wt = jnp.concatenate(
            [wt, jnp.zeros((v_pad - V, E), wt.dtype)], axis=0)
    wt = wt.reshape(n_chunks, chunk, E).astype(h.dtype)
    N = h.shape[0]
    neg = jnp.float32(-1e30)

    def tile(carry, xs):
        mx, s, ll = carry
        idx, w_c = xs
        logits = jnp.einsum("ne,ce->nc", h, w_c,
                            preferred_element_type=jnp.float32)
        col = idx * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < V, logits, neg)
        m_new = jnp.maximum(mx, jnp.max(logits, axis=-1))
        s = s * jnp.exp(mx - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        ll = ll + jnp.sum(
            jnp.where(col[None, :] == y[:, None], logits, 0.0), axis=-1)
        return (m_new, s, ll), None

    init = (jnp.full((N,), neg, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    (mx, s, ll), _ = jax.lax.scan(
        jax.checkpoint(tile), init, (jnp.arange(n_chunks), wt))
    per_tok = mx + jnp.log(s) - ll
    msk = m.astype(per_tok.dtype)
    return jnp.sum(per_tok * msk), jnp.sum(msk)


def classification_loss(logits: jax.Array, labels: jax.Array
                        ) -> tuple[jax.Array, jax.Array]:
    """Mean CE for the toy classification harnesses (the reference's MNIST
    smoke path, hivetrain/training_manager.py:462-644). logits [B, C],
    labels [B] int. Returns (mean_loss, example_count) with the same
    aggregation contract as causal_lm_loss."""
    per_ex = cross_entropy_with_logits(logits, labels)
    count = jnp.asarray(per_ex.shape[0], jnp.float32)
    return jnp.sum(per_ex) / count, count


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
