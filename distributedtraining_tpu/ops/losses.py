"""Loss functions for causal LM training/eval.

Reproduces the reference's HF-style ``labels=input_ids`` shifted
cross-entropy (hivetrain/training_manager.py:380-385,
hivetrain/validation_logic.py:86-91) as explicit jittable functions, with
fp32 log-softmax over bf16 logits and padding-aware token counting (the
reference masks pad via HF's internal -100 handling; here the mask is an
explicit argument).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token CE, fp32. logits [..., V], labels [...] int."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - label_logits


def causal_lm_loss(logits: jax.Array, input_ids: jax.Array,
                   loss_mask: Optional[jax.Array] = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Shifted next-token loss.

    logits: [B, T, V]; input_ids: [B, T]; loss_mask: [B, T] 1.0 where the
    *label* token is real (pad and cross-segment boundaries excluded by the
    data pipeline).

    Returns (mean_loss, token_count) — token_count lets callers aggregate
    exactly across shards/batches (sum(loss*count)/sum(count)).
    """
    shift_logits = logits[:, :-1, :]
    shift_labels = input_ids[:, 1:]
    per_tok = cross_entropy_with_logits(shift_logits, shift_labels)
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(per_tok.dtype)
    else:
        m = jnp.ones_like(per_tok)
    total = jnp.sum(per_tok * m)
    count = jnp.maximum(jnp.sum(m), 1.0)
    return total / count, count


def perplexity(mean_loss: jax.Array) -> jax.Array:
    """The validator's second metric (hivetrain/validation_logic.py:93-97)."""
    return jnp.exp(mean_loss)


def classification_loss(logits: jax.Array, labels: jax.Array
                        ) -> tuple[jax.Array, jax.Array]:
    """Mean CE for the toy classification harnesses (the reference's MNIST
    smoke path, hivetrain/training_manager.py:462-644). logits [B, C],
    labels [B] int. Returns (mean_loss, example_count) with the same
    aggregation contract as causal_lm_loss."""
    per_ex = cross_entropy_with_logits(logits, labels)
    count = jnp.asarray(per_ex.shape[0], jnp.float32)
    return jnp.sum(per_ex) / count, count


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
