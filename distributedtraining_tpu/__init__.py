"""distributedtraining_tpu — a TPU-native incentivized federated-training framework.

Capability-parity rebuild of bit-current/DistributedTraining ("Hivetrain"):
independent *miners* train weight-deltas of a shared base LM, *validators*
score each delta by measured loss improvement on held-out data and emit the
scores to a (Bittensor-style) chain, and an *averager* merges deltas with
learned mixing weights into the next base model. Coordination rides artifact
repositories (HF-Hub-style) plus a chain key-value/score plane — not a
collective-communication fabric — so every participant can come and go.

Unlike the PyTorch reference, all compute here is JAX/XLA:

- train / eval steps are jitted pure functions (engine/train.py, engine/validate.py)
- the parameterized merge is a jitted computation over a stacked miner axis
  with ``jax.grad`` supplying merge-weight meta-gradients (engine/average.py)
- intra-node scaling is a ``jax.sharding.Mesh`` (dp/fsdp/tp/sp axes) over ICI
  (parallel/), with ring attention for long sequences (ops/ring_attention.py)
- deltas round-trip as msgpack / safetensors, never pickle (serialization.py)

Reference layer map: see SURVEY.md at the repo root.
"""

__version__ = "0.5.0"

# Spec version emitted with chain weight-sets (reference:
# template/__init__.py:24-27 encodes version -> int for set_weights).
def spec_version() -> int:
    major, minor, patch = (int(x) for x in __version__.split("."))
    return (1000 * major) + (10 * minor) + patch
