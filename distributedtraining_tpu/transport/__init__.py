"""Artifact transport: the framework's tensor plane.

The reference's "communication backend" is HuggingFace Hub git repos
(hivetrain/hf_manager.py): miners push ``weight_diff.pt`` to per-miner repos,
the averager pushes ``averaged_model.pt`` to a shared repo, and everyone
polls commit SHAs for change detection. Here the same contract is a
``Transport`` protocol with three interchangeable backends:

- InMemoryTransport — process-local dicts (unit tests, simulations)
- LocalFSTransport  — directory + content-hash revisions (the reference's
  LocalHFManager twin, hf_manager.py:200-241, made first-class)
- HFHubTransport    — the real Hub: safetensors/msgpack artifacts, commit-SHA
  revisions, history squashing as GC (network-gated)
- SignedTransport   — Ed25519 authenticity envelope over any of the above
  (signs publishes, verifies fetches against registered pubkeys)

All payloads cross the boundary as validated msgpack/safetensors — never
pickle.
"""

from .base import Transport, Revision
from .chaos import ChaosError, ChaosEvent, ChaosSpec, ChaosTransport
from .memory import InMemoryTransport
from .localfs import LocalFSTransport
from .retry import RetryPolicy, call_with_retry

__all__ = ["Transport", "Revision", "InMemoryTransport", "LocalFSTransport",
           "SignedTransport", "HFHubTransport", "RetryPolicy",
           "call_with_retry", "ChaosTransport", "ChaosSpec", "ChaosEvent",
           "ChaosError"]


def __getattr__(name):
    # lazy: importing the package must require neither huggingface_hub nor
    # cryptography (SignedTransport -> signing -> utils.identity pulls the
    # latter; both are optional extras)
    if name == "HFHubTransport":
        from .hf_hub import HFHubTransport
        return HFHubTransport
    if name == "SignedTransport":
        from .signed import SignedTransport
        return SignedTransport
    raise AttributeError(name)
