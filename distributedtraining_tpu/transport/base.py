"""Transport protocol.

Method mapping to the reference's HFManager (hivetrain/hf_manager.py):

| here                      | reference                                  |
|---------------------------|--------------------------------------------|
| publish_delta             | push_changes("weight_diff.pt") :91-114     |
| fetch_delta               | receive_gradients :186-197                 |
| publish_base              | push_to_hf_hub("averaged_model.pt") :116-136 |
| fetch_base                | pull_latest_model + update_model :161-184  |
| base_revision             | check_for_new_submissions (shared repo) :151-159 |
| delta_revision            | check_for_new_submissions (miner repo)     |
| gc                        | super_squash_history + git lfs prune :73-114 |

Revisions are opaque strings (commit SHA / content hash); ``None`` means "no
artifact yet". Change detection is revision inequality, exactly like the
reference's commit-SHA polling.
"""

from __future__ import annotations

from typing import Any, Optional, Protocol

Params = Any
Revision = Optional[str]


class Transport(Protocol):
    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        """Upload this miner's current weight delta (overwrites previous)."""
        ...

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped, possibly hostile)
        delta bytes — SignedTransport publishes through this, and the load
        generator uses it to simulate miners that don't run our code."""
        ...

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        """Download + validate a miner's delta; None if absent or invalid.
        Must tolerate (strip, unverified) signature envelopes."""
        ...

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw size-capped artifact bytes, one network read — for
        multi-template validation (full-param vs LoRA wire forms) and for
        SignedTransport's signature verification. Envelopes are returned
        INTACT here."""
        ...

    def delta_revision(self, miner_id: str) -> Revision:
        ...

    # -- base model (averager publishes, everyone pulls) -------------------
    def publish_base(self, base: Params) -> Revision:
        ...

    def publish_base_raw(self, data: bytes) -> Revision:
        """Byte-level twin of publish_base (signature envelopes)."""
        ...

    def fetch_base(self, template: Params) -> tuple[Params, Revision] | None:
        ...

    def fetch_base_bytes(self) -> bytes | None:
        """Raw base bytes, envelope intact (SignedTransport verification)."""
        ...

    def base_revision(self) -> Revision:
        ...

    # -- lifecycle ----------------------------------------------------------
    def gc(self) -> None:
        """Bound storage (the reference squashes git history + prunes LFS)."""
        ...
