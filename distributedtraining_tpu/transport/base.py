"""Transport protocol.

Method mapping to the reference's HFManager (hivetrain/hf_manager.py):

| here                      | reference                                  |
|---------------------------|--------------------------------------------|
| publish_delta             | push_changes("weight_diff.pt") :91-114     |
| fetch_delta               | receive_gradients :186-197                 |
| publish_base              | push_to_hf_hub("averaged_model.pt") :116-136 |
| fetch_base                | pull_latest_model + update_model :161-184  |
| base_revision             | check_for_new_submissions (shared repo) :151-159 |
| delta_revision            | check_for_new_submissions (miner repo)     |
| gc                        | super_squash_history + git lfs prune :73-114 |

Revisions are opaque strings (commit SHA / content hash); ``None`` means "no
artifact yet". Change detection is revision inequality, exactly like the
reference's commit-SHA polling.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Protocol

Params = Any
Revision = Optional[str]

META_MAX_BYTES = 4096

# ---------------------------------------------------------------------------
# Heartbeat naming contract (the fleet health plane, engine/health.py)
# ---------------------------------------------------------------------------
# Heartbeats are compact JSON documents that ride the DELTA-META channel
# under a RESERVED artifact id, so every transport (and every wrapper:
# SignedTransport passes riders through, CoordinatorGatedTransport gates
# the write to the pod coordinator) carries them with zero new backend
# code — they travel exactly like delta riders do today. The reserved
# prefix keeps them out of the metagraph's hotkey namespace: chain
# hotkeys never start with it, and delta consumers never stage it.

HEARTBEAT_PREFIX = "__hb__"

# Failover leases (engine/remediate.py) ride the same reserved-id channel:
# a tiny JSON token naming the current publication holder and a
# monotonically increasing epoch. One reserved id per contended role —
# today only the averager's base publication is single-writer.
LEASE_PREFIX = "__lease__"


def heartbeat_id(role: str, node_id: str) -> str:
    """The reserved per-node artifact id heartbeats publish under.
    ``role`` disambiguates a hotkey running several roles on one fleet
    (a validator and an averager may share storage)."""
    return f"{HEARTBEAT_PREFIX}.{role}.{node_id}"


def is_heartbeat_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(HEARTBEAT_PREFIX + ".")


def lease_id(role: str = "averager") -> str:
    """The reserved artifact id a role's publication lease lives under."""
    return f"{LEASE_PREFIX}.{role}"


def is_reserved_id(artifact_id: str) -> bool:
    """True for any id in the reserved control-plane namespace (heartbeats,
    leases) — delta consumers must never stage these as submissions."""
    return isinstance(artifact_id, str) and (
        artifact_id.startswith(HEARTBEAT_PREFIX + ".")
        or artifact_id.startswith(LEASE_PREFIX + "."))


def encode_delta_meta(meta: dict) -> bytes:
    """Serialize a metadata rider (tiny JSON; size-capped on read)."""
    return json.dumps(meta).encode()


def parse_delta_meta(data: bytes | None) -> dict | None:
    """Parse PEER-CONTROLLED rider bytes defensively: size-capped, must be
    a JSON object, and the protocol-read key (``base_revision``) must be a
    short string. Anything else reads as None (= no rider = reference
    accept-anything behavior), never an exception."""
    if data is None or len(data) > META_MAX_BYTES:
        return None
    try:
        meta = json.loads(data)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(meta, dict):
        return None
    rev = meta.get("base_revision")
    if rev is not None and not (isinstance(rev, str) and len(rev) <= 200):
        return None
    return meta


class Transport(Protocol):
    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        """Upload this miner's current weight delta (overwrites previous)."""
        ...

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped, possibly hostile)
        delta bytes — SignedTransport publishes through this, and the load
        generator uses it to simulate miners that don't run our code."""
        ...

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        """Download + validate a miner's delta; None if absent or invalid.
        Must tolerate (strip, unverified) signature envelopes."""
        ...

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw size-capped artifact bytes, one network read — for
        multi-template validation (full-param vs LoRA wire forms) and for
        SignedTransport's signature verification. Envelopes are returned
        INTACT here."""
        ...

    def delta_revision(self, miner_id: str) -> Revision:
        """Current revision of the miner's delta artifact, or None when
        absent. CONTRACT: this must be cheap relative to the artifact
        fetch (a commit-SHA read, a stat-cached content hash) — the
        ingest cache (engine/ingest.py) probes it once per miner per
        round and skips the download entirely when it is unchanged, so a
        probe that costs like a download erases the point. It must also
        be stable: equal revisions MUST imply identical artifact bytes
        (the cache serves the decoded tree keyed on it)."""
        ...

    # -- delta metadata rider (optional; absent = reference behavior) ------
    # The same channel carries fleet heartbeats under the reserved
    # ``heartbeat_id(role, hotkey)`` ids (module-level contract above):
    # implementations must treat those ids like any other per-miner id
    # (opaque strings), which all built-ins already do.
    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        """Small JSON rider next to the delta artifact. The one key the
        protocol reads is ``base_revision`` — the base the delta was
        computed against — which lets receivers detect STALE deltas (a
        delta vs base N applied to base N+1 re-adds the part of the
        N->N+1 update the miner had already incorporated; the reference
        silently double-applies). Peer-controlled: readers must treat the
        contents as untrusted."""
        ...

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        """The rider for ``miner_id``, or None (absent/unparseable —
        receivers then fall back to the reference's accept-anything)."""
        ...

    # -- base model (averager publishes, everyone pulls) -------------------
    def publish_base(self, base: Params) -> Revision:
        ...

    def publish_base_raw(self, data: bytes) -> Revision:
        """Byte-level twin of publish_base (signature envelopes)."""
        ...

    def fetch_base(self, template: Params) -> tuple[Params, Revision] | None:
        ...

    def fetch_base_bytes(self) -> bytes | None:
        """Raw base bytes, envelope intact (SignedTransport verification)."""
        ...

    def base_revision(self) -> Revision:
        ...

    # -- lifecycle ----------------------------------------------------------
    def gc(self) -> None:
        """Bound storage (the reference squashes git history + prunes LFS)."""
        ...
