"""Transport protocol.

Method mapping to the reference's HFManager (hivetrain/hf_manager.py):

| here                      | reference                                  |
|---------------------------|--------------------------------------------|
| publish_delta             | push_changes("weight_diff.pt") :91-114     |
| fetch_delta               | receive_gradients :186-197                 |
| publish_base              | push_to_hf_hub("averaged_model.pt") :116-136 |
| fetch_base                | pull_latest_model + update_model :161-184  |
| base_revision             | check_for_new_submissions (shared repo) :151-159 |
| delta_revision            | check_for_new_submissions (miner repo)     |
| gc                        | super_squash_history + git lfs prune :73-114 |

Revisions are opaque strings (commit SHA / content hash); ``None`` means "no
artifact yet". Change detection is revision inequality, exactly like the
reference's commit-SHA polling.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Protocol

Params = Any
Revision = Optional[str]

META_MAX_BYTES = 4096

# ---------------------------------------------------------------------------
# Heartbeat naming contract (the fleet health plane, engine/health.py)
# ---------------------------------------------------------------------------
# Heartbeats are compact JSON documents that ride the DELTA-META channel
# under a RESERVED artifact id, so every transport (and every wrapper:
# SignedTransport passes riders through, CoordinatorGatedTransport gates
# the write to the pod coordinator) carries them with zero new backend
# code — they travel exactly like delta riders do today. The reserved
# prefix keeps them out of the metagraph's hotkey namespace: chain
# hotkeys never start with it, and delta consumers never stage it.

HEARTBEAT_PREFIX = "__hb__"

# Failover leases (engine/remediate.py) ride the same reserved-id channel:
# a tiny JSON token naming the current publication holder and a
# monotonically increasing epoch. One reserved id per contended role —
# today only the averager's base publication is single-writer.
LEASE_PREFIX = "__lease__"

# Hierarchical aggregation (engine/hier_average.py): a sub-averager
# publishes its cohort's PARTIAL AGGREGATE — an ordinary delta artifact
# (dense v1 or a wire-v2 shard manifest) holding the weighted average of
# its assigned miners' deltas — under a reserved per-node id, and the
# root averager stages those ids exactly like miner submissions (same
# ingest pool, same cache, same screens). The reserved prefix keeps
# aggregates out of the metagraph hotkey namespace: a FLAT consumer
# syncing hotkeys from the chain can never stage one by accident; the
# ROOT stages them deliberately from its configured node list. The
# aggregate's weight-sum rides the delta-META channel (an ``"agg"``
# rider key, validated defensively at ingest), so the root's mixing
# weights are per-subtree without any new transport surface.

AGG_PREFIX = "__agg__"

# Wire-v2 per-layer delta shards (serialization.py shard container,
# engine/publish.py uploads, engine/ingest.py fetches): each shard is
# raw bytes under a reserved per-(miner, layer) id, so every byte-capable
# transport carries them through its existing publish_raw /
# fetch_delta_bytes surface with zero new backend code. The id is
# LAYER-stable (a re-publish of a layer overwrites its previous shard —
# the same storage-bounding overwrite rule as every other artifact);
# the CONTENT address lives in the signed/validated manifest's per-shard
# sha256, which ingest verifies on every fetch. Transports with a richer
# namespace (HF Hub: one repo per miner) may implement
# publish_shard/fetch_shard methods instead; the module helpers below
# prefer those.
SHARD_PREFIX = "__shard__"

# Postmortem bundles (utils/flight.py): when a role's flight recorder
# freezes — SLO breach, remediation action, crash hook — the bundle (a
# content-addressed JSON document of the ring's recent events + registry
# snapshot + sanitized config) publishes under a reserved per-(role,
# hotkey) id through the SAME byte surface deltas use. That is the whole
# point: forensics from a node that is about to die travel like any
# other artifact — chaos-gated, signed when the fleet signs
# (publish_delta_raw envelopes them), coordinator-gated on pods — and a
# SURVIVOR fetches a dead peer's bundle from its storage slot exactly
# like a delta (fetch_delta_bytes). Each freeze overwrites the previous
# bundle (the storage-bounding overwrite rule); the full bundle history
# survives in the role's metrics JSONL stream.
PM_PREFIX = "__pm__"

# consumer-side size cap for one bundle read (utils/flight.PM_MAX_BYTES
# is the producer-side truncation bound; same number, one contract)
PM_MAX_BYTES = 1 << 20

# Lineage records (engine/lineage.py): every time the averager (or a
# sub-averager) lands a merge, it freezes a content-addressed JSON
# record — parent base revision, the exact (hotkey, cid, weight,
# wire bytes, verdict, score) set that entered the merge, and the
# resulting revision — published under a reserved PER-REVISION id
# through the SAME byte surface deltas use (publish_delta_raw when the
# transport offers it, so a signed fleet's provenance is attributable;
# chaos-gated; coordinator-gated on pods). Unlike the __pm__/__hb__
# slots, the id is keyed on the RESULTING revision, so records are
# never overwritten: together they form the provenance DAG rooted at
# the seed checkpoint, and any validator can fetch a revision's record
# and re-derive the merge (`scripts/lineage_report.py --replay`).
# Records are tiny (KBs) — the storage bound is the record cap below,
# not the overwrite rule.
LINEAGE_PREFIX = "__lineage__"

# consumer-side size cap for one lineage record read (the producer
# truncates contributions to fit; same number, one contract —
# engine/lineage.LINEAGE_MAX_BYTES mirrors it)
LINEAGE_MAX_BYTES = 1 << 18

# Content-addressed base distribution (engine/basedist.py): the averager
# publishes the new base AS hash-addressed per-layer shards plus one
# small signed manifest, next to (not instead of) the monolithic
# ``publish_base`` artifact — the monolithic blob stays the source of
# truth and the mixed-fleet fallback, while sharded fetchers diff the
# manifest against their local shard store and pull ONLY changed-hash
# layers (a warm-round base pull is KBs; an unchanged layer is 0
# bytes — the same dedupe economics the wire-v2 delta path proved).
#
#   __base__.s.<layer-slug>   one base shard (layer-stable slot,
#                             overwritten each publish like delta
#                             shards; the content address rides the
#                             manifest)
#   __base__.<revision-slug>  the manifest for one published base
#                             revision (keyed on the revision like
#                             __lineage__ records, so a fetcher that
#                             observed base_revision() == R reads
#                             exactly R's shard set; manifests are KBs
#                             — the storage bound is the manifest cap)
#
# The ``__base__`` id itself carries the averager's BASE-WIRE META
# rider (``{"base_wire": {...}}``) declaring the plane, the current
# revision, and the mirror list — the v1/v2-style negotiation: a
# fetcher that reads no rider (old averager) never probes for
# manifests and stays on the monolithic pull.
BASE_PREFIX = "__base__"

# consumer-side size cap for one base manifest read
# (serialization.BASE_MANIFEST_MAX_BYTES mirrors it; same number, one
# contract)
BASE_MANIFEST_MAX_BYTES = 1 << 20

# Disaggregated serving KV transfer (engine/kv_transfer.py): a prefill
# worker exports one finished request's KV pages as content-addressed
# shards and a per-request manifest, and a decode worker adopts them —
# the serving twin of the ``__base__`` sharded plane, with the same
# manifest-last publication order (shards first, manifest last, so a
# torn set is never decodable and the reader degrades to local
# prefill).
#
#   __kv__.s.<digest>        one KV page's bytes, keyed on its sha256
#                            content address (idempotent re-publish;
#                            shared system-prompt pages dedupe on the
#                            wire for free)
#   __kv__.<request-slug>    the per-request KV manifest (page digest
#                            list + page geometry + base revision),
#                            published LAST
KV_PREFIX = "__kv__"

# consumer-side size caps: one KV manifest is KBs of JSON; one KV page
# is [L, P, Hkv, D] x {k, v} — bounded by geometry, capped generously
KV_MANIFEST_MAX_BYTES = 1 << 20
KV_PAGE_MAX_BYTES = 1 << 26

# Regional shard mirrors (engine/basedist.MirrorDuty): an ``__agg__``
# sub-averager re-publishes the base shards it already pulled under its
# own reserved per-node namespace, and fetchers race/pick ANY replica
# that has the hash (shards are verified against the signed manifest's
# sha256 whatever slot served them, so a hostile or stale mirror can at
# worst serve bytes that fail their hash check). The origin incast
# becomes a fan-out tree built from roles the fleet already runs; any
# single mirror dying is a non-event (fetchers fall through to origin).
#
#   __mirror__.<node>                       the mirror's presence rider
#                                           slot ({"mirror": {...}})
#   __shard__.__mirror__.<node>.<slug>      its shard replicas (via
#                                           shard_id(mirror_node_id(n)))
MIRROR_PREFIX = "__mirror__"


def heartbeat_id(role: str, node_id: str) -> str:
    """The reserved per-node artifact id heartbeats publish under.
    ``role`` disambiguates a hotkey running several roles on one fleet
    (a validator and an averager may share storage)."""
    return f"{HEARTBEAT_PREFIX}.{role}.{node_id}"


def is_heartbeat_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(HEARTBEAT_PREFIX + ".")


def lease_id(role: str = "averager") -> str:
    """The reserved artifact id a role's publication lease lives under."""
    return f"{LEASE_PREFIX}.{role}"


def agg_id(node_id: str) -> str:
    """The reserved artifact id one sub-averager's partial aggregate
    travels under. ``node_id`` is the sub-averager's stable node name
    (its hotkey by default) — the id every round's re-publish overwrites,
    exactly like a miner's delta id."""
    return f"{AGG_PREFIX}.{node_id}"


def is_agg_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(AGG_PREFIX + ".")


def shard_layer_slug(layer_key: str) -> str:
    """Filename/id-safe spelling of a manifest layer key ("/"-joined
    state-dict path). Injective: literal "%" and "." inside components
    are percent-escaped BEFORE "/" maps to ".", so keys like "a/b.c"
    and "a/b/c" get distinct shard ids instead of silently overwriting
    each other's shards (components never contain "/" themselves —
    delta.packed_layer_entries enforces it at pack time)."""
    return (layer_key.replace("%", "%25").replace(".", "%2E")
            .replace("/", "."))


def shard_id(hotkey: str, layer_key: str) -> str:
    """The reserved artifact id one miner's per-layer shard travels
    under on id-namespace transports (localfs, memory)."""
    return f"{SHARD_PREFIX}.{hotkey}.{shard_layer_slug(layer_key)}"


def is_shard_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(SHARD_PREFIX + ".")


def pm_id(role: str, node_id: str) -> str:
    """The reserved artifact id a (role, hotkey)'s postmortem bundle
    publishes under — role-qualified like heartbeat ids, because one
    hotkey may run several roles against one store and each role's
    forensics are distinct."""
    return f"{PM_PREFIX}.{role}.{node_id}"


def is_pm_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(PM_PREFIX + ".")


def lineage_slug(revision: str) -> str:
    """Filename/id-safe spelling of an opaque revision string, injective
    by the same percent-escape rule as :func:`shard_layer_slug` (a
    revision from a commit-SHA or content-hash transport is already
    safe; the escape covers exotic backends)."""
    return (str(revision).replace("%", "%25").replace(".", "%2E")
            .replace("/", "%2F"))


def lineage_id(revision: str) -> str:
    """The reserved artifact id the lineage record for ``revision``
    publishes under. Keyed on the RESULTING revision (never overwritten
    — each merge's record is a new DAG node), unlike the per-node
    heartbeat/postmortem slots."""
    return f"{LINEAGE_PREFIX}.{lineage_slug(revision)}"


def is_lineage_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(LINEAGE_PREFIX + ".")


def base_shard_id(layer_key: str) -> str:
    """The reserved artifact id one base layer's shard travels under on
    id-namespace transports. Reuses :func:`shard_layer_slug`, so the
    layer-key -> id mapping is injective by the same percent-escape
    rule as delta shards (``a/b.c`` and ``a/b/c`` never collide). The
    ``s.`` segment keeps shard ids disjoint from manifest ids: a
    revision slug contains no literal ``.`` (lineage_slug escapes
    them), so no manifest id can spell ``s.<anything-with-a-dot>``."""
    return f"{BASE_PREFIX}.s.{shard_layer_slug(layer_key)}"


def base_manifest_id(revision: str) -> str:
    """The reserved artifact id the base manifest for ``revision``
    publishes under — keyed on the revision (like ``__lineage__``
    records), so a fetcher that probed ``base_revision() == R`` reads
    exactly R's shard set and a mid-publish race degrades to the
    monolithic fallback instead of a torn decode."""
    return f"{BASE_PREFIX}.{lineage_slug(revision)}"


def is_base_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(BASE_PREFIX + ".")


def mirror_node_id(node_id: str) -> str:
    """The reserved pseudo-hotkey one mirror's replicas travel under:
    its shards ride ``shard_id(mirror_node_id(node), layer_key)`` and
    its presence rider rides the ``__mirror__.<node>`` meta slot —
    both through surfaces every transport already has."""
    return f"{MIRROR_PREFIX}.{node_id}"


def is_mirror_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(MIRROR_PREFIX + ".")


def kv_page_id(digest: str) -> str:
    """The reserved artifact id one exported KV page travels under,
    keyed on its sha256 content address. The ``s.`` segment keeps page
    ids disjoint from manifest ids by the same rule as
    :func:`base_shard_id` (a request slug never contains a literal
    ``.`` — :func:`lineage_slug` escapes them)."""
    return f"{KV_PREFIX}.s.{digest}"


def kv_manifest_id(request_id: str) -> str:
    """The reserved artifact id the KV manifest for one request
    publishes under — keyed on the request id (reqtrace mints them
    unique per submission), slug-escaped by :func:`lineage_slug` so
    exotic request ids stay id-safe."""
    return f"{KV_PREFIX}.{lineage_slug(request_id)}"


def is_kv_id(artifact_id: str) -> bool:
    return isinstance(artifact_id, str) and \
        artifact_id.startswith(KV_PREFIX + ".")


def is_reserved_id(artifact_id: str) -> bool:
    """True for any id in the reserved control-plane/shard/aggregate/
    postmortem namespace (heartbeats, leases, wire-v2 shards, partial
    aggregates, flight-recorder bundles) — FLAT delta consumers must
    never stage these as miner submissions (the hierarchy root stages
    ``__agg__.*`` ids deliberately, from its configured node list,
    never from the metagraph)."""
    return isinstance(artifact_id, str) and (
        artifact_id.startswith(HEARTBEAT_PREFIX + ".")
        or artifact_id.startswith(LEASE_PREFIX + ".")
        or artifact_id.startswith(SHARD_PREFIX + ".")
        or artifact_id.startswith(AGG_PREFIX + ".")
        or artifact_id.startswith(PM_PREFIX + ".")
        or artifact_id.startswith(LINEAGE_PREFIX + ".")
        or artifact_id == BASE_PREFIX
        or artifact_id.startswith(BASE_PREFIX + ".")
        or artifact_id.startswith(KV_PREFIX + ".")
        or artifact_id.startswith(MIRROR_PREFIX + "."))


def publish_postmortem(transport, role: str, node_id: str,
                       data: bytes) -> None:
    """Publish one frozen bundle's bytes under the reserved pm id.
    Prefers ``publish_delta_raw`` (SignedTransport envelopes it under
    the delta context — a signed fleet's forensics are attributable),
    falling back to ``publish_raw`` on plain transports."""
    pdr = getattr(transport, "publish_delta_raw", None)
    if pdr is not None:
        pdr(pm_id(role, node_id), data)
        return
    transport.publish_raw(pm_id(role, node_id), data)


def fetch_postmortem_bytes(transport, role: str,
                           node_id: str) -> bytes | None:
    """Raw (possibly enveloped, size-capped) bundle bytes for one
    (role, hotkey), or None — validation and envelope-stripping live in
    utils/flight.fetch_bundle, the same split as delta reads."""
    data = transport.fetch_delta_bytes(pm_id(role, node_id))
    if data is not None and len(data) > PM_MAX_BYTES:
        return None
    return data


def publish_lineage(transport, revision: str, data: bytes) -> None:
    """Publish one lineage record's bytes under the reserved per-revision
    id. Prefers ``publish_delta_raw`` (SignedTransport envelopes it under
    the delta context — a signed fleet's provenance is attributable),
    falling back to ``publish_raw`` on plain transports — the exact
    split :func:`publish_postmortem` uses."""
    pdr = getattr(transport, "publish_delta_raw", None)
    if pdr is not None:
        pdr(lineage_id(revision), data)
        return
    transport.publish_raw(lineage_id(revision), data)


def fetch_lineage_bytes(transport, revision: str) -> bytes | None:
    """Raw (possibly enveloped, size-capped) lineage record bytes for one
    revision, or None — validation, envelope-stripping, and the content-
    address check live in engine/lineage.fetch_record, the same split as
    postmortem reads."""
    data = transport.fetch_delta_bytes(lineage_id(revision))
    if data is not None and len(data) > LINEAGE_MAX_BYTES:
        return None
    return data


def publish_shard(transport, hotkey: str, layer_key: str,
                  data: bytes) -> None:
    """Publish one shard through whatever surface ``transport`` offers:
    its own ``publish_shard`` method when present (HF Hub stores a file
    per layer inside the miner's repo), else ``publish_raw`` under the
    reserved shard id. Wrappers (signed/chaos) delegate explicitly so
    the inner transport's preference survives the wrapping."""
    ps = getattr(transport, "publish_shard", None)
    if ps is not None:
        ps(hotkey, layer_key, data)
        return
    transport.publish_raw(shard_id(hotkey, layer_key), data)


def fetch_shard(transport, hotkey: str, layer_key: str) -> bytes | None:
    """Fetch one shard's raw bytes (or None). Integrity is NOT this
    layer's job — callers verify the bytes against the manifest's
    content hash (engine/ingest.py), which is what makes unsigned shard
    transport safe under SignedTransport: the hash rides the signed
    manifest."""
    fs = getattr(transport, "fetch_shard", None)
    if fs is not None:
        return fs(hotkey, layer_key)
    return transport.fetch_delta_bytes(shard_id(hotkey, layer_key))


def publish_base_shard(transport, layer_key: str, data: bytes) -> None:
    """Publish one BASE shard through whatever surface ``transport``
    offers: its own ``publish_base_shard`` method when present (HF Hub
    stores a file inside the shared averaged-model repo), else
    ``publish_raw`` under the reserved ``__base__.s.*`` id. Like delta
    shards, base shards travel UNSIGNED — their integrity is the
    sha256 the (signed) base manifest pins."""
    ps = getattr(transport, "publish_base_shard", None)
    if ps is not None:
        ps(layer_key, data)
        return
    transport.publish_raw(base_shard_id(layer_key), data)


def fetch_base_shard(transport, layer_key: str) -> bytes | None:
    """One base shard's raw bytes from the ORIGIN slot (or None);
    callers verify against the manifest hash (engine/basedist.py)."""
    fs = getattr(transport, "fetch_base_shard", None)
    if fs is not None:
        return fs(layer_key)
    return transport.fetch_delta_bytes(base_shard_id(layer_key))


def publish_base_manifest(transport, revision: str, data: bytes) -> None:
    """Publish one base manifest's bytes under the reserved
    per-revision id. Prefers ``publish_delta_raw`` (SignedTransport
    envelopes it — the fetched shard set's hashes are then
    attributable to the averager), falling back to ``publish_raw`` on
    plain transports — the exact split :func:`publish_lineage` uses."""
    pbm = getattr(transport, "publish_base_manifest", None)
    if pbm is not None:
        pbm(revision, data)
        return
    pdr = getattr(transport, "publish_delta_raw", None)
    if pdr is not None:
        pdr(base_manifest_id(revision), data)
        return
    transport.publish_raw(base_manifest_id(revision), data)


def fetch_base_manifest_bytes(transport, revision: str) -> bytes | None:
    """Raw (possibly enveloped, size-capped) base manifest bytes for
    one revision, or None — validation and envelope handling live in
    engine/basedist.py, the same split as lineage reads. Absence is
    the v1 negotiation signal: no manifest means monolithic fetch."""
    fbm = getattr(transport, "fetch_base_manifest", None)
    data = (fbm(revision) if fbm is not None
            else transport.fetch_delta_bytes(base_manifest_id(revision)))
    if data is not None and len(data) > BASE_MANIFEST_MAX_BYTES:
        return None
    return data


def publish_kv_page(transport, digest: str, data: bytes) -> None:
    """Publish one exported KV page through whatever surface
    ``transport`` offers: its own ``publish_kv_page`` method when
    present, else ``publish_raw`` under the reserved ``__kv__.s.*``
    id. Like delta/base shards, KV pages travel UNSIGNED — their
    integrity is the sha256 content address the manifest pins (and the
    id itself spells)."""
    pk = getattr(transport, "publish_kv_page", None)
    if pk is not None:
        pk(digest, data)
        return
    transport.publish_raw(kv_page_id(digest), data)


def fetch_kv_page(transport, digest: str) -> bytes | None:
    """One KV page's raw bytes (or None); callers verify against the
    digest (engine/kv_transfer.py) — unsigned transport is safe
    because the hash rides the manifest."""
    fk = getattr(transport, "fetch_kv_page", None)
    if fk is not None:
        return fk(digest)
    data = transport.fetch_delta_bytes(kv_page_id(digest))
    if data is not None and len(data) > KV_PAGE_MAX_BYTES:
        return None
    return data


def publish_kv_manifest(transport, request_id: str, data: bytes) -> None:
    """Publish one request's KV manifest under the reserved
    per-request id — LAST, after every page it lists (manifest-last
    publication; a reader that sees the manifest sees a complete page
    set or degrades on a hash miss). Prefers ``publish_delta_raw``
    (SignedTransport envelopes it — the adopted page hashes are then
    attributable to the prefill worker), the exact split
    :func:`publish_base_manifest` uses."""
    pkm = getattr(transport, "publish_kv_manifest", None)
    if pkm is not None:
        pkm(request_id, data)
        return
    pdr = getattr(transport, "publish_delta_raw", None)
    if pdr is not None:
        pdr(kv_manifest_id(request_id), data)
        return
    transport.publish_raw(kv_manifest_id(request_id), data)


def fetch_kv_manifest_bytes(transport, request_id: str) -> bytes | None:
    """Raw (possibly enveloped, size-capped) KV manifest bytes for one
    request, or None — validation lives in engine/kv_transfer.py, the
    same split as base-manifest reads. Absence means the prefill leg
    never completed publication: the decode worker prefills locally."""
    fkm = getattr(transport, "fetch_kv_manifest", None)
    data = (fkm(request_id) if fkm is not None
            else transport.fetch_delta_bytes(kv_manifest_id(request_id)))
    if data is not None and len(data) > KV_MANIFEST_MAX_BYTES:
        return None
    return data


def encode_delta_meta(meta: dict) -> bytes:
    """Serialize a metadata rider (tiny JSON; size-capped on read)."""
    return json.dumps(meta).encode()


def parse_delta_meta(data: bytes | None) -> dict | None:
    """Parse PEER-CONTROLLED rider bytes defensively: size-capped, must be
    a JSON object, and the protocol-read key (``base_revision``) must be a
    short string. Anything else reads as None (= no rider = reference
    accept-anything behavior), never an exception."""
    if data is None or len(data) > META_MAX_BYTES:
        return None
    try:
        meta = json.loads(data)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(meta, dict):
        return None
    rev = meta.get("base_revision")
    if rev is not None and not (isinstance(rev, str) and len(rev) <= 200):
        return None
    return meta


class Transport(Protocol):
    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        """Upload this miner's current weight delta (overwrites previous)."""
        ...

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped, possibly hostile)
        delta bytes — SignedTransport publishes through this, and the load
        generator uses it to simulate miners that don't run our code."""
        ...

    # OPTIONAL (wrappers only; callers fall back to publish_raw via
    # getattr): bytes that ARE this node's own delta artifact — the
    # wire-v2 manifest publish goes through here so SignedTransport can
    # envelope it under the delta context exactly like a publish_delta,
    # while plain transports treat it as publish_raw. Distinct from
    # publish_raw, whose contract is "pass hostile bytes through
    # untouched".
    # def publish_delta_raw(self, miner_id: str, data: bytes) -> Revision

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        """Download + validate a miner's delta; None if absent or invalid.
        Must tolerate (strip, unverified) signature envelopes."""
        ...

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw size-capped artifact bytes, one network read — for
        multi-template validation (full-param vs LoRA wire forms) and for
        SignedTransport's signature verification. Envelopes are returned
        INTACT here."""
        ...

    def delta_revision(self, miner_id: str) -> Revision:
        """Current revision of the miner's delta artifact, or None when
        absent. CONTRACT: this must be cheap relative to the artifact
        fetch (a commit-SHA read, a stat-cached content hash) — the
        ingest cache (engine/ingest.py) probes it once per miner per
        round and skips the download entirely when it is unchanged, so a
        probe that costs like a download erases the point. It must also
        be stable: equal revisions MUST imply identical artifact bytes
        (the cache serves the decoded tree keyed on it)."""
        ...

    # -- delta metadata rider (optional; absent = reference behavior) ------
    # The same channel carries fleet heartbeats under the reserved
    # ``heartbeat_id(role, hotkey)`` ids (module-level contract above):
    # implementations must treat those ids like any other per-miner id
    # (opaque strings), which all built-ins already do.
    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        """Small JSON rider next to the delta artifact. The one key the
        protocol reads is ``base_revision`` — the base the delta was
        computed against — which lets receivers detect STALE deltas (a
        delta vs base N applied to base N+1 re-adds the part of the
        N->N+1 update the miner had already incorporated; the reference
        silently double-applies). Peer-controlled: readers must treat the
        contents as untrusted."""
        ...

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        """The rider for ``miner_id``, or None (absent/unparseable —
        receivers then fall back to the reference's accept-anything)."""
        ...

    # -- base model (averager publishes, everyone pulls) -------------------
    def publish_base(self, base: Params) -> Revision:
        ...

    def publish_base_raw(self, data: bytes) -> Revision:
        """Byte-level twin of publish_base (signature envelopes)."""
        ...

    def fetch_base(self, template: Params) -> tuple[Params, Revision] | None:
        ...

    def fetch_base_bytes(self) -> bytes | None:
        """Raw base bytes, envelope intact (SignedTransport verification)."""
        ...

    def base_revision(self) -> Revision:
        ...

    # -- lifecycle ----------------------------------------------------------
    def gc(self) -> None:
        """Bound storage (the reference squashes git history + prunes LFS)."""
        ...
