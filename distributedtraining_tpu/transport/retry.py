"""Bounded retry with jittered exponential backoff for transport publishes.

The reference retries uploads with ad-hoc fixed loops (a blocking double
retry in the rider path, bare try/except elsewhere). This is the ONE home
of the retry rule for every publish — the async publisher worker
(engine/publish.py) and the sync push path both call through here, so the
two paths cannot drift on attempt counts or pacing.

Jitter matters at fleet scale: a hundred miners whose pushes all fail on
the same Hub hiccup would otherwise re-hit it in lockstep at exactly
base_delay, 2*base_delay, ... — the classic retry storm. The +/-``jitter``
fraction decorrelates them.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """attempts = TOTAL tries (1 = no retry). Delay before try n+1 is
    ``base_delay * 2**(n-1)`` capped at ``max_delay``, scaled by a uniform
    factor in [1-jitter, 1+jitter].

    ``max_elapsed`` is a TOTAL-ELAPSED deadline (seconds) across the whole
    retry loop — calls plus backoff sleeps. An attempt budget alone is the
    wrong bound on a PARTITIONED backend: each try can block for its full
    transport timeout (tens of seconds on a black-holed TCP connection),
    so "3 attempts" can silently eat a whole round. Once the deadline
    passes — or the next backoff sleep would overshoot it — the loop
    abandons remaining attempts and re-raises, counted as
    ``transport.retry_deadline`` so a fleet report can tell deadline
    abandonment from ordinary budget exhaustion. None = no deadline."""
    attempts: int = 3
    base_delay: float = 0.25
    max_delay: float = 8.0
    jitter: float = 0.5
    max_elapsed: Optional[float] = None

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")
        if self.max_elapsed is not None and self.max_elapsed <= 0:
            raise ValueError(f"max_elapsed must be > 0 or None, "
                             f"got {self.max_elapsed}")

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff after the ``attempt``-th (1-based) failed try."""
        d = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        return max(0.0, d * (1.0 + self.jitter * (2.0 * rng.random() - 1.0)))


# the rider is tiny and best-effort; the artifact is the protocol payload.
# The elapsed deadlines are generous next to the attempt budgets (which
# bound the healthy case); they exist for the PARTITIONED case, where a
# single blocked call can otherwise exceed the round cadence.
DEFAULT_PUBLISH_RETRY = RetryPolicy(attempts=3, base_delay=0.25,
                                    max_delay=8.0, max_elapsed=120.0)
DEFAULT_META_RETRY = RetryPolicy(attempts=3, base_delay=0.1, max_delay=2.0,
                                 max_elapsed=30.0)
# ingest-side reads (revision probes, artifact fetches): a shorter budget
# than publishes — a missed miner this round scores/merges next round,
# whereas a lost publish drops the artifact entirely. Failures after the
# budget are isolated PER MINER by the ingest pool (engine/ingest.py),
# never round-fatal.
DEFAULT_FETCH_RETRY = RetryPolicy(attempts=2, base_delay=0.2, max_delay=2.0,
                                  max_elapsed=60.0)


def call_with_retry(fn: Callable, *, policy: RetryPolicy | None = None,
                    sleep: Callable[[float], None] = time.sleep,
                    rng: Optional[random.Random] = None,
                    describe: str = "publish",
                    monotonic: Callable[[], float] = time.monotonic):
    """Run ``fn`` under ``policy``; returns its value or raises the LAST
    failure once the attempt budget is spent (callers decide whether a
    terminal failure is fatal — for a miner push it never is).

    ``sleep`` is injectable so loops pass their Clock's sleep (FakeClock
    tests retry pacing in microseconds) and workers stay real-time;
    ``monotonic`` pairs with it so the ``max_elapsed`` deadline is
    testable on the same fake timebase.

    Every try feeds the observability registry (utils/obs.py, no-ops
    unless a sink is configured): ``transport.retry.attempts`` counts
    total tries, ``transport.retry.retries`` the failed-then-retried
    ones, ``transport.retry.exhausted`` spent budgets,
    ``transport.retry_deadline`` the retries abandoned because
    ``max_elapsed`` ran out mid-loop, and ``transport.retry.call_ms`` the
    per-try latency — the fleet-level view of a flaky Hub that per-role
    logs cannot show."""
    from ..utils import obs

    policy = policy or DEFAULT_PUBLISH_RETRY
    rng = rng or random.Random()
    start = monotonic()
    for attempt in range(1, policy.attempts + 1):
        obs.count("transport.retry.attempts")
        t0 = time.perf_counter()
        try:
            out = fn()
        except Exception as e:
            obs.observe("transport.retry.call_ms",
                        (time.perf_counter() - t0) * 1e3)
            if attempt >= policy.attempts:
                obs.count("transport.retry.exhausted")
                raise
            delay = policy.delay(attempt, rng)
            if policy.max_elapsed is not None and \
                    monotonic() - start + delay > policy.max_elapsed:
                # the next sleep would overshoot the round budget: a retry
                # loop on a partitioned backend must surrender the rest of
                # its attempts rather than blow the cadence it serves
                obs.count("transport.retry_deadline")
                logger.warning(
                    "%s failed (attempt %d/%d); abandoning %d remaining "
                    "attempt(s) — %.1fs elapsed of the %.1fs deadline: %s",
                    describe, attempt, policy.attempts,
                    policy.attempts - attempt,
                    monotonic() - start, policy.max_elapsed, e)
                raise
            obs.count("transport.retry.retries")
            logger.warning("%s failed (attempt %d/%d), retrying in %.2fs: %s",
                           describe, attempt, policy.attempts, delay, e)
            sleep(delay)
        else:
            obs.observe("transport.retry.call_ms",
                        (time.perf_counter() - t0) * 1e3)
            return out
