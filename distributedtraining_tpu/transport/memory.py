"""In-memory transport: the fastest test backend.

Stores serialized bytes (not live pytrees) so the full serialize → validate →
deserialize path runs exactly as it would over the wire.
"""

from __future__ import annotations

import hashlib
from typing import Any

from .. import serialization as ser
from .. import signing
from .base import Revision, encode_delta_meta, parse_delta_meta

Params = Any


class InMemoryTransport:
    def __init__(self):
        self._deltas: dict[str, bytes] = {}
        self._delta_meta: dict[str, bytes] = {}
        self._base: bytes | None = None
        # revision cache, computed at publish: ingest probes every miner's
        # revision every round (engine/ingest.py), and re-hashing a
        # full-model payload per probe is O(model bytes) of pure CPU for
        # bytes that did not change
        self._delta_revs: dict[str, str] = {}
        self._base_rev: str | None = None

    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        return self.publish_raw(miner_id, ser.to_msgpack(delta))

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Arbitrary bytes as a 'delta' — hostile-miner simulation for the
        admission screens (utils/loadgen.py); a real adversary is not
        obliged to run our serializer."""
        self._deltas[miner_id] = bytes(data)
        self._delta_revs[miner_id] = hashlib.sha256(
            self._deltas[miner_id]).hexdigest()
        return self._delta_revs[miner_id]

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        data = self._deltas.get(miner_id)
        if data is None:
            return None
        try:
            # envelope-tolerant without verification (verification lives in
            # SignedTransport, which reads the raw-bytes path)
            return ser.validated_load(signing.strip_envelope(data), template)
        except ser.PayloadError:
            return None

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw artifact bytes, one fetch — callers that must validate
        against several templates (full-param vs LoRA adapter) run all
        attempts on the same payload."""
        return self._deltas.get(miner_id)

    def delta_revision(self, miner_id: str) -> Revision:
        if miner_id not in self._deltas:
            return None
        rev = self._delta_revs.get(miner_id)
        if rev is None:  # bytes injected behind the API (test doubles)
            rev = self._delta_revs[miner_id] = hashlib.sha256(
                self._deltas[miner_id]).hexdigest()
        return rev

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        self._delta_meta[miner_id] = encode_delta_meta(meta)

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        return parse_delta_meta(self._delta_meta.get(miner_id))

    # -- base model ---------------------------------------------------------
    def publish_base(self, base: Params) -> Revision:
        return self.publish_base_raw(ser.to_msgpack(base))

    def publish_base_raw(self, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped) base bytes."""
        self._base = bytes(data)
        self._base_rev = hashlib.sha256(self._base).hexdigest()
        return self._base_rev

    def fetch_base_bytes(self) -> bytes | None:
        return self._base

    def fetch_base(self, template: Params):
        if self._base is None:
            return None
        try:
            tree = ser.validated_load(signing.strip_envelope(self._base),
                                      template)
        except ser.PayloadError:
            return None
        return tree, self.base_revision()

    def base_revision(self) -> Revision:
        if self._base is None:
            return None
        if self._base_rev is None:  # bytes injected behind the API
            self._base_rev = hashlib.sha256(self._base).hexdigest()
        return self._base_rev

    def gc(self) -> None:
        pass  # nothing accumulates: publishes overwrite
