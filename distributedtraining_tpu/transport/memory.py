"""In-memory transport: the fastest test backend.

Stores serialized bytes (not live pytrees) so the full serialize → validate →
deserialize path runs exactly as it would over the wire.
"""

from __future__ import annotations

import hashlib
from typing import Any

from .. import serialization as ser
from .. import signing
from .base import Revision, encode_delta_meta, parse_delta_meta

Params = Any


class InMemoryTransport:
    def __init__(self):
        self._deltas: dict[str, bytes] = {}
        self._delta_meta: dict[str, bytes] = {}
        self._base: bytes | None = None

    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        self._deltas[miner_id] = ser.to_msgpack(delta)
        return self.delta_revision(miner_id)

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Arbitrary bytes as a 'delta' — hostile-miner simulation for the
        admission screens (utils/loadgen.py); a real adversary is not
        obliged to run our serializer."""
        self._deltas[miner_id] = bytes(data)
        return self.delta_revision(miner_id)

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        data = self._deltas.get(miner_id)
        if data is None:
            return None
        try:
            # envelope-tolerant without verification (verification lives in
            # SignedTransport, which reads the raw-bytes path)
            return ser.validated_load(signing.strip_envelope(data), template)
        except ser.PayloadError:
            return None

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw artifact bytes, one fetch — callers that must validate
        against several templates (full-param vs LoRA adapter) run all
        attempts on the same payload."""
        return self._deltas.get(miner_id)

    def delta_revision(self, miner_id: str) -> Revision:
        data = self._deltas.get(miner_id)
        return None if data is None else hashlib.sha256(data).hexdigest()

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        self._delta_meta[miner_id] = encode_delta_meta(meta)

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        return parse_delta_meta(self._delta_meta.get(miner_id))

    # -- base model ---------------------------------------------------------
    def publish_base(self, base: Params) -> Revision:
        self._base = ser.to_msgpack(base)
        return self.base_revision()

    def publish_base_raw(self, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped) base bytes."""
        self._base = bytes(data)
        return self.base_revision()

    def fetch_base_bytes(self) -> bytes | None:
        return self._base

    def fetch_base(self, template: Params):
        if self._base is None:
            return None
        try:
            tree = ser.validated_load(signing.strip_envelope(self._base),
                                      template)
        except ser.PayloadError:
            return None
        return tree, self.base_revision()

    def base_revision(self) -> Revision:
        return None if self._base is None else hashlib.sha256(self._base).hexdigest()

    def gc(self) -> None:
        pass  # nothing accumulates: publishes overwrite
