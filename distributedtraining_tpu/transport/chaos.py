"""ChaosTransport: deterministic fault injection over any Transport.

The remediation layer (engine/remediate.py) exists for the failure modes
fleet-scale operation makes routine — wedged miners, partitioned
backends, a dead averager — and none of those can be provoked reliably
by "run it long enough and hope". This wrapper (same decorator pattern
as transport/signed.py) makes every failure mode an *input*:

- **error rates**: each publish/fetch class of operation independently
  fails with a configured probability, drawn from a SEEDED
  ``random.Random`` whose consumption order is fixed (one draw per
  faultable operation, in call order), so a given (seed, call sequence)
  always produces the same fault sequence — tests assert exact outcomes,
  not distributions;
- **latency**: a fixed per-operation sleep (plus optional deterministic
  jitter from the same seeded stream), the cheap stand-in for a slow Hub;
- **partitions**: per-hotkey unreachability — every operation naming a
  partitioned hotkey raises, everything else proceeds, which is how a
  "that one miner's repo is down" round is simulated;
- **kill switches per role**: ``kill_role("averager")`` makes EVERY
  operation through a transport owned by that role raise — the in-process
  spelling of kill -9 as seen from the node's own I/O (the process is
  "up" but can neither publish nor fetch), which is what drives the
  failover tests without multiprocess orchestration;
- **schedule**: an ordered list of ``(at_op, action, target)`` events
  applied as the global operation counter passes ``at_op`` — "kill the
  miner on its 7th transport operation" is deterministic however the
  surrounding threads interleave their own clocks.

Faults are ordinary ``ChaosError`` (an ``OSError``) so every existing
isolation path — per-miner staging isolation, retry policies, publish
failure counters — exercises exactly the code it would on a real outage.

Injected faults are counted in the obs registry (``chaos.faults``,
``chaos.<kind>_faults``) so a chaos soak's report shows how much abuse
the run absorbed next to how it behaved.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import random
import threading
import time
from typing import Any, Callable, Sequence

from ..utils import obs

logger = logging.getLogger(__name__)

Params = Any


class ChaosError(OSError):
    """An injected transport fault (an OSError so retry/isolation paths
    treat it exactly like a real backend failure)."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """Static fault configuration (the schedule/toggles are runtime state
    on the transport). Rates are per-operation probabilities in [0, 1].

    ``latency_jitter`` scales the fixed latency by a deterministic factor
    in [1-j, 1+j] drawn from the seeded stream, so latency variation is
    reproducible too.
    """
    publish_error_rate: float = 0.0
    fetch_error_rate: float = 0.0
    latency_s: float = 0.0
    latency_jitter: float = 0.0
    partitioned: tuple = ()          # hotkeys unreachable from the start
    killed_roles: tuple = ()         # roles dead from the start
    seed: int = 0

    def __post_init__(self):
        for name in ("publish_error_rate", "fetch_error_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.latency_s < 0:
            raise ValueError(f"latency_s must be >= 0, got {self.latency_s}")
        if not 0.0 <= self.latency_jitter <= 1.0:
            raise ValueError(f"latency_jitter must be in [0, 1], "
                             f"got {self.latency_jitter}")

    @classmethod
    def from_json(cls, text: str) -> "ChaosSpec":
        """Build from a JSON object (the --chaos-spec CLI surface). Lists
        become tuples; unknown keys are an error — a typo'd rate silently
        injecting nothing defeats the point of a chaos run."""
        raw = json.loads(text)
        if not isinstance(raw, dict):
            raise ValueError(f"chaos spec must be a JSON object, got "
                             f"{type(raw).__name__}")
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            raise ValueError(f"unknown chaos spec keys {sorted(unknown)}; "
                             f"expected a subset of {sorted(fields)}")
        for k in ("partitioned", "killed_roles"):
            if k in raw:
                raw[k] = tuple(raw[k])
        return cls(**raw)


# one schedule event: when the GLOBAL op counter reaches ``at_op``, apply
# ``action`` ("kill_role" | "revive_role" | "partition" | "heal") to
# ``target``. Events are sorted by at_op and applied at most once.
@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    at_op: int
    action: str
    target: str

    _ACTIONS = ("kill_role", "revive_role", "partition", "heal")

    def __post_init__(self):
        if self.action not in self._ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r}; "
                             f"expected one of {self._ACTIONS}")


class ChaosTransport:
    """Wrap ``inner`` with the fault model of ``spec``.

    ``role`` is the OWNING role's name (what ``kill_role`` matches);
    ``sleep`` is injectable so tests run latency schedules on a fake
    clock. Runtime toggles (:meth:`kill_role` etc.) and the event
    schedule mutate shared state under a lock — the ingest pool calls in
    from its worker threads.
    """

    def __init__(self, inner, spec: ChaosSpec | None = None, *,
                 role: str | None = None,
                 schedule: Sequence[ChaosEvent] | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.inner = inner
        self.spec = spec or ChaosSpec()
        self.role = role
        self._sleep = sleep
        self._rng = random.Random(self.spec.seed)
        self._lock = threading.Lock()
        self._partitioned: set[str] = set(self.spec.partitioned)
        self._killed: set[str] = set(self.spec.killed_roles)
        self._schedule = sorted(schedule or (), key=lambda e: e.at_op)
        self._next_event = 0
        self.ops = 0            # global op counter (drives the schedule)
        self.faults = 0

    # -- runtime fault control ----------------------------------------------
    def kill_role(self, role: str) -> None:
        with self._lock:
            self._killed.add(role)

    def revive_role(self, role: str) -> None:
        with self._lock:
            self._killed.discard(role)

    def partition(self, hotkey: str) -> None:
        with self._lock:
            self._partitioned.add(hotkey)

    def heal(self, hotkey: str) -> None:
        with self._lock:
            self._partitioned.discard(hotkey)

    def partitioned(self) -> set[str]:
        with self._lock:
            return set(self._partitioned)

    # -- the fault gate ------------------------------------------------------
    def _apply(self, event: ChaosEvent) -> None:
        logger.info("chaos: op %d -> %s(%s)", self.ops, event.action,
                    event.target)
        if event.action == "kill_role":
            self._killed.add(event.target)
        elif event.action == "revive_role":
            self._killed.discard(event.target)
        elif event.action == "partition":
            self._partitioned.add(event.target)
        else:
            self._partitioned.discard(event.target)

    def _fault(self, kind: str, detail: str) -> None:
        self.faults += 1
        obs.count("chaos.faults")
        obs.count(f"chaos.{kind}_faults")
        raise ChaosError(f"chaos[{kind}]: {detail}")

    def _gate(self, kind: str, hotkey: str | None = None) -> None:
        """One faultable operation: advance the schedule, then kill switch
        -> partition -> latency -> error rate, in that order (a dead node
        fails fast; only a live, reachable one pays latency). EXACTLY ONE
        rate draw happens per gate whatever the outcome, so the seeded
        stream stays aligned across runs that toggle switches
        differently."""
        with self._lock:
            self.ops += 1
            while (self._next_event < len(self._schedule)
                   and self._schedule[self._next_event].at_op <= self.ops):
                self._apply(self._schedule[self._next_event])
                self._next_event += 1
            rate = (self.spec.publish_error_rate if kind == "publish"
                    else self.spec.fetch_error_rate)
            roll = self._rng.random()
            jitter = (self._rng.uniform(1 - self.spec.latency_jitter,
                                        1 + self.spec.latency_jitter)
                      if self.spec.latency_jitter else 1.0)
            killed = self.role is not None and self.role in self._killed
            cut = hotkey is not None and hotkey in self._partitioned
        if killed:
            self._fault("killed", f"role {self.role} is killed")
        if cut:
            self._fault("partition", f"hotkey {hotkey} is partitioned")
        if self.spec.latency_s > 0:
            self._sleep(self.spec.latency_s * jitter)
        if rate > 0 and roll < rate:
            self._fault(kind, f"injected {kind} error "
                              f"(rate {rate:g}, op {self.ops})")

    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params):
        self._gate("publish", miner_id)
        return self.inner.publish_delta(miner_id, delta)

    def publish_raw(self, miner_id: str, data: bytes):
        self._gate("publish", miner_id)
        return self.inner.publish_raw(miner_id, data)

    def publish_delta_raw(self, miner_id: str, data: bytes):
        self._gate("publish", miner_id)
        pdr = getattr(self.inner, "publish_delta_raw", None)
        if pdr is not None:
            return pdr(miner_id, data)
        return self.inner.publish_raw(miner_id, data)

    # wire-v2 shard ops: each shard publish/fetch is its own faultable
    # operation (that is exactly how a mid-publish failure tears a shard
    # set — the torn-set test drives this gate)
    def publish_shard(self, hotkey: str, layer_key: str, data: bytes):
        from . import base
        self._gate("publish", hotkey)
        ps = getattr(self.inner, "publish_shard", None)
        if ps is not None:
            return ps(hotkey, layer_key, data)
        return self.inner.publish_raw(base.shard_id(hotkey, layer_key), data)

    def fetch_shard(self, hotkey: str, layer_key: str):
        from . import base
        self._gate("fetch", hotkey)
        fs = getattr(self.inner, "fetch_shard", None)
        if fs is not None:
            return fs(hotkey, layer_key)
        return self.inner.fetch_delta_bytes(base.shard_id(hotkey, layer_key))

    # base-distribution ops (engine/basedist.py): each shard / manifest
    # publish or fetch is its own faultable operation — a mid-publish
    # fault is exactly how a torn base shard set happens, and a fetch
    # fault is how a fetcher's mirror-failover path gets exercised.
    # Delegation re-dispatches the module helper on the INNER transport
    # so a wrapped backend's own surface (and signing preference) is
    # preserved through the gate.
    def publish_base_shard(self, layer_key: str, data: bytes):
        from . import base
        self._gate("publish")
        return base.publish_base_shard(self.inner, layer_key, data)

    def fetch_base_shard(self, layer_key: str):
        from . import base
        self._gate("fetch")
        return base.fetch_base_shard(self.inner, layer_key)

    def publish_base_manifest(self, revision: str, data: bytes):
        from . import base
        self._gate("publish")
        return base.publish_base_manifest(self.inner, revision, data)

    def fetch_base_manifest(self, revision: str):
        from . import base
        self._gate("fetch")
        return base.fetch_base_manifest_bytes(self.inner, revision)

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        self._gate("publish", miner_id)
        pm = getattr(self.inner, "publish_delta_meta", None)
        if pm is not None:
            pm(miner_id, meta)

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params):
        self._gate("fetch", miner_id)
        return self.inner.fetch_delta(miner_id, template)

    def fetch_delta_bytes(self, miner_id: str):
        self._gate("fetch", miner_id)
        return self.inner.fetch_delta_bytes(miner_id)

    def fetch_delta_meta(self, miner_id: str):
        self._gate("fetch", miner_id)
        fm = getattr(self.inner, "fetch_delta_meta", None)
        return fm(miner_id) if fm is not None else None

    def delta_revision(self, miner_id: str):
        self._gate("fetch", miner_id)
        return self.inner.delta_revision(miner_id)

    # -- base model ---------------------------------------------------------
    def publish_base(self, base: Params):
        self._gate("publish")
        return self.inner.publish_base(base)

    def publish_base_raw(self, data: bytes):
        self._gate("publish")
        return self.inner.publish_base_raw(data)

    def fetch_base(self, template: Params):
        self._gate("fetch")
        return self.inner.fetch_base(template)

    def fetch_base_bytes(self):
        self._gate("fetch")
        return self.inner.fetch_base_bytes()

    def base_revision(self):
        self._gate("fetch")
        return self.inner.base_revision()

    # -- lifecycle ----------------------------------------------------------
    def gc(self) -> None:
        # storage bounding is driver machinery, not a protocol operation —
        # faulting it would test nothing the publish/fetch gates don't
        self.inner.gc()
