"""SignedTransport: Ed25519 authenticity over any byte-capable transport.

Wraps a Transport whose artifacts are raw bytes on the wire (LocalFS,
InMemory, HFHub all qualify) and:

- signs every publish with this node's Identity (signing.wrap), binding the
  artifact kind and hotkey into the signed message so a delta can never be
  replayed as a base or under another hotkey;
- verifies every fetch against the hotkey's *registered* public key
  (``pubkey_resolver``, normally AddressStore.retrieve_pubkey). Policy:

    | artifact state        | key registered | no key registered        |
    |-----------------------|----------------|--------------------------|
    | valid envelope        | accept         | accept                   |
    | forged/tampered       | reject         | reject                   |
    | unsigned              | reject         | accept unless ``strict`` |

  A registered key makes signatures mandatory for that hotkey — an attacker
  who can write artifacts but not sign them cannot "downgrade" to unsigned.

The reference's equivalent trust anchor is HF repo ownership plus
hotkey-signed metric posts (hivetrain/utils/dummy_miner.py:63-68); this
closes the same hole for deployments with no repo ownership (LocalFS, the
peer registry) and defends HF deployments against hijacked repos too.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

from .. import serialization as ser
from .. import signing
from .base import Revision

logger = logging.getLogger(__name__)

Params = Any
PubkeyResolver = Callable[[str], Optional[bytes]]


class SignedTransport:
    def __init__(self, inner, *, identity=None,
                 pubkey_resolver: PubkeyResolver | None = None,
                 base_signer: str | None = None,
                 my_hotkey: str | None = None,
                 strict: bool = False,
                 max_bytes: int = ser.DEFAULT_MAX_BYTES,
                 now_fn=None):
        """``identity``: this node's signing key (None = fetch-only role).
        ``base_signer``: hotkey expected to sign the published base (the
        averager); with a registered key for it, base fetches require a
        valid signature. ``my_hotkey``: this node's PROTOCOL hotkey — the
        domain-separation context for its base publishes must match what
        peers configure as ``base_signer`` (pubkeys are registered under
        protocol hotkeys, not derived identity ids). ``strict``: refuse ALL
        unsigned artifacts."""
        import time
        self.inner = inner
        self.identity = identity
        self.pubkey_resolver = pubkey_resolver or (lambda hotkey: None)
        self.base_signer = base_signer
        self.my_hotkey = my_hotkey or (identity.hotkey if identity else "")
        self.strict = strict
        self.max_bytes = max_bytes
        self._now = now_fn or time.time
        # anti-rollback watermark: the highest base sequence this node has
        # accepted. An attacker with write access replaying an OLD validly
        # signed base changes the content hash (a "new" revision) but not
        # the signed sequence — monotonicity rejects it. In-memory only: a
        # freshly booted node accepts the first base it sees (bounded
        # protection; persistent pinning would need chain-side anchoring).
        self._base_seq_seen = 0

    # -- policy -------------------------------------------------------------
    def _open(self, data: bytes, hotkey: str, context: bytes) -> bytes:
        expected = self.pubkey_resolver(hotkey)
        return signing.unwrap(data, context, expected_pub=expected,
                              require=self.strict or expected is not None)

    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        data = ser.to_msgpack(delta)
        if self.identity is not None:
            data = signing.wrap(data, self.identity,
                                signing.delta_context(miner_id))
        return self.inner.publish_raw(miner_id, data)

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Pass-through (hostile-miner simulation publishes unsigned/forged
        bytes on purpose — utils/loadgen.py)."""
        return self.inner.publish_raw(miner_id, data)

    def publish_delta_raw(self, miner_id: str, data: bytes) -> Revision:
        """This node's OWN delta artifact as pre-built bytes (the wire-v2
        manifest): enveloped under the delta context exactly like
        publish_delta, so receivers verify it against this hotkey's
        registered key."""
        if self.identity is not None:
            data = signing.wrap(data, self.identity,
                                signing.delta_context(miner_id))
        return self.inner.publish_raw(miner_id, data)

    # -- wire-v2 shards ------------------------------------------------------
    # Shards travel UNSIGNED: their integrity is the sha256 the (signed)
    # manifest carries, which ingest verifies on every fetch — enveloping
    # each of ~150 per-layer shards would buy nothing the manifest hash
    # doesn't already pin, and would break strict-mode fleets whose shard
    # ids have no registered keys. Explicit delegation keeps the inner
    # transport's own shard surface (HF Hub's file-per-layer) reachable
    # through the wrapper.
    def publish_shard(self, hotkey: str, layer_key: str,
                      data: bytes) -> None:
        from . import base
        sp = getattr(self.inner, "publish_shard", None)
        if sp is not None:
            sp(hotkey, layer_key, data)
            return
        self.inner.publish_raw(base.shard_id(hotkey, layer_key), data)

    def fetch_shard(self, hotkey: str, layer_key: str) -> bytes | None:
        from . import base
        fs = getattr(self.inner, "fetch_shard", None)
        if fs is not None:
            return fs(hotkey, layer_key)
        return self.inner.fetch_delta_bytes(base.shard_id(hotkey, layer_key))

    # -- base-distribution shards (engine/basedist.py) -----------------------
    # Same policy as delta shards: base shards travel UNSIGNED (their
    # integrity is the sha256 in the signed base manifest, verified by
    # every fetcher whatever replica served the bytes), so these
    # delegate past the envelope machinery — a strict-mode fleet must
    # not reject hash-pinned shards for lacking a signature the
    # manifest already provides. The MANIFEST itself publishes through
    # publish_delta_raw (transport/base.publish_base_manifest prefers
    # it), so it IS enveloped and verified like a delta artifact.
    def publish_base_shard(self, layer_key: str, data: bytes) -> None:
        from . import base
        ps = getattr(self.inner, "publish_base_shard", None)
        if ps is not None:
            ps(layer_key, data)
            return
        self.inner.publish_raw(base.base_shard_id(layer_key), data)

    def fetch_base_shard(self, layer_key: str) -> bytes | None:
        from . import base
        fs = getattr(self.inner, "fetch_base_shard", None)
        if fs is not None:
            return fs(layer_key)
        return self.inner.fetch_delta_bytes(base.base_shard_id(layer_key))

    # -- validator / averager side -----------------------------------------
    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        raw = self.inner.fetch_delta_bytes(miner_id)
        if raw is None:
            return None
        try:
            return self._open(raw, miner_id, signing.delta_context(miner_id))
        except ser.PayloadError as e:
            logger.warning("delta from %s rejected: %s", miner_id, e)
            return None

    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        data = self.fetch_delta_bytes(miner_id)
        if data is None:
            return None
        try:
            return ser.validated_load(data, template,
                                      max_bytes=self.max_bytes)
        except ser.PayloadError:
            return None

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        """Rider passthrough. Not enveloped: a forged rider can at worst
        (a) re-enable the reference's own accept-stale behavior for this
        miner, or (b) mark the miner's fresh delta stale — self-harm that
        skip-policy receivers answer by dropping it for one push
        interval. The artifact itself stays signature-verified either
        way."""
        pm = getattr(self.inner, "publish_delta_meta", None)
        if pm is not None:
            pm(miner_id, meta)

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        fm = getattr(self.inner, "fetch_delta_meta", None)
        return fm(miner_id) if fm is not None else None

    def delta_revision(self, miner_id: str) -> Revision:
        return self.inner.delta_revision(miner_id)

    # -- base model ---------------------------------------------------------
    def publish_base(self, base: Params) -> Revision:
        data = ser.to_msgpack(base)
        if self.identity is not None:
            # the signed context carries a monotonic sequence (unix time):
            # peers reject bases whose sequence goes backwards, so a
            # replayed old-but-validly-signed base cannot roll the fleet back
            ctx = (signing.base_context(self.my_hotkey)
                   + b":" + str(int(self._now())).encode())
            data = signing.wrap(data, self.identity, ctx)
        return self.inner.publish_base_raw(data)

    def _open_base(self, raw: bytes) -> bytes | None:
        """With ``base_signer`` configured, the envelope must carry exactly
        that identity's context and key (mandatory once the key is
        registered). Without it there is no trust anchor to bind identity
        to, but the artifact KIND is still enforced — a signed delta
        replayed as a base is rejected either way."""
        signer = self.base_signer
        try:
            if signer:
                prefix = signing.base_context(signer)
                expected = self.pubkey_resolver(signer)
                payload, ctx = signing.unwrap_with_context(
                    raw, context_prefix=prefix,
                    expected_pub=expected,
                    require=self.strict or expected is not None)
                seq = signing.context_seq(ctx, prefix)
                if seq and seq < self._base_seq_seen:
                    raise ser.PayloadError(
                        f"base sequence rolled back ({seq} < "
                        f"{self._base_seq_seen}) — replayed stale base")
                self._base_seq_seen = max(self._base_seq_seen, seq)
                return payload
            return signing.unwrap(raw, kind=b"base", require=self.strict)
        except ser.PayloadError as e:
            logger.warning("published base rejected: %s", e)
            return None

    def fetch_base(self, template: Params):
        raw = self.inner.fetch_base_bytes()
        if raw is None:
            return None
        data = self._open_base(raw)
        if data is None:
            return None
        try:
            tree = ser.validated_load(data, template,
                                      max_bytes=self.max_bytes)
        except ser.PayloadError:
            return None
        return tree, self.inner.base_revision()

    def base_revision(self) -> Revision:
        return self.inner.base_revision()

    def publish_base_raw(self, data: bytes) -> Revision:
        """Pass-through (pre-built bytes are the caller's responsibility to
        envelope — the hostile/simulation path, like publish_raw)."""
        return self.inner.publish_base_raw(data)

    def fetch_base_bytes(self) -> bytes | None:
        """Raw base bytes, envelope intact — a second verifying layer or a
        byte-level broadcast reads through this untouched."""
        return self.inner.fetch_base_bytes()

    # -- lifecycle ----------------------------------------------------------
    def gc(self) -> None:
        self.inner.gc()
