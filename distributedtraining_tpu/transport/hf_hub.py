"""HuggingFace Hub transport — the production tensor plane.

Parity with hivetrain/hf_manager.py, minus its hazards:

- artifacts are msgpack/safetensors, never pickled .pt (ref: torch.load of
  untrusted peer files, hf_manager.py:186-197)
- uploads use the HTTP API (upload_file) instead of a local git clone per
  repo, so there is no blocking git subprocess in the training loop
- change detection = commit-SHA polling (ref: check_for_new_submissions,
  hf_manager.py:151-159)
- gc = server-side history squash (ref: super_squash_history + lfs prune,
  hf_manager.py:73-114)

Network-gated: constructing it without huggingface_hub installed or a token
raises a clear error; everything in-process still works through the
InMemory/LocalFS backends.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any

from .. import serialization as ser
from ..utils import obs
from .base import Revision

Params = Any

DELTA_FILE = "weight_diff.msgpack"
META_FILE = "weight_diff.meta.json"
BASE_FILE = "averaged_model.msgpack"


class HFHubTransport:
    def __init__(self, *, averaged_model_repo_id: str,
                 my_repo_id: str | None = None,
                 token: str | None = None,
                 max_bytes: int = ser.DEFAULT_MAX_BYTES,
                 owns_base_repo: bool = False,
                 api: Any | None = None):
        if api is None:
            try:
                import huggingface_hub  # noqa: F401
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "HFHubTransport requires huggingface_hub; use "
                    "LocalFSTransport/InMemoryTransport for offline operation"
                ) from e
            from huggingface_hub import HfApi
            api = HfApi(token=token or os.environ.get("HF_TOKEN"))

        self.api = api
        self.my_repo_id = my_repo_id
        self.base_repo_id = averaged_model_repo_id
        self.max_bytes = max_bytes
        # which repos this node may squash: its own delta repo, plus the
        # shared averaged-model repo when this node is the averager that
        # owns it (the reference squashes BOTH repos, hf_manager.py:73-136;
        # a validator squashing someone else's shared repo would 403)
        self.owns_base_repo = owns_base_repo
        # miner_id -> repo_id mapping is supplied by the chain store
        # (chain/base.py); transports only see repo ids.

    # -- helpers ------------------------------------------------------------
    def _upload(self, repo_id: str, filename: str, tree: Params) -> Revision:
        """Tree publish: serialization STREAMS leaf-by-leaf straight into
        the spooled temp file (ser.to_msgpack_file). The old spelling
        materialized the full msgpack payload in memory AND copied it to
        the temp file — 2x peak host RSS per push at the 8B scale, paid on
        the publisher worker every send interval."""
        with tempfile.NamedTemporaryFile(suffix=".msgpack",
                                         delete=False) as f:
            ser.to_msgpack_file(tree, f)
            tmp = f.name
        return self._upload_path(repo_id, filename, tmp)

    def _upload_bytes(self, repo_id: str, filename: str,
                      data: bytes) -> Revision:
        with tempfile.NamedTemporaryFile(suffix=".msgpack", delete=False) as f:
            f.write(data)
            tmp = f.name
        return self._upload_path(repo_id, filename, tmp)

    def _upload_path(self, repo_id: str, filename: str, tmp: str) -> Revision:
        try:
            info = self.api.upload_file(
                path_or_fileobj=tmp, path_in_repo=filename,
                repo_id=repo_id, repo_type="model")
        finally:
            os.unlink(tmp)
        return getattr(info, "oid", None) or self._revision(repo_id)

    def _download_bytes(self, repo_id: str, filename: str,
                        max_bytes: int | None = None) -> bytes | None:
        """One network download -> capped raw bytes; the cached blob is
        deleted after reading to bound disk (hf_manager.py:195).
        ``max_bytes`` overrides the delta-sized default for small files
        (the rider cap — a hostile GB-sized meta.json must die at the
        size check, not get read into memory)."""
        from huggingface_hub.utils import EntryNotFoundError, RepositoryNotFoundError
        try:
            # routed through the api object (not the module function) so a
            # stub HfApi exercises the full download path in tests
            path = self.api.hf_hub_download(repo_id=repo_id, filename=filename)
        except (EntryNotFoundError, RepositoryNotFoundError):
            return None
        try:
            if os.path.getsize(path) > (max_bytes or self.max_bytes):
                return None
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            return None
        finally:
            try:
                os.unlink(os.path.realpath(path))
            except OSError:
                pass

    def _download(self, repo_id: str, filename: str,
                  template: Params) -> Params | None:
        data = self._download_bytes(repo_id, filename)
        if data is None:
            return None
        try:
            # envelope-tolerant without verification (verification lives in
            # SignedTransport, which reads the raw-bytes path)
            from .. import signing
            return ser.from_msgpack(signing.strip_envelope(data), template,
                                    max_bytes=self.max_bytes)
        except ser.PayloadError:
            return None

    def _revision(self, repo_id: str) -> Revision:
        """Commit-SHA probe (one small API call, no LFS pull) — cheap
        enough for the ingest cache to issue once per miner per round
        (engine/ingest.py); the counter makes the fleet's probe volume
        visible next to its download volume."""
        obs.count("transport.revision_probes")
        try:
            refs = self.api.list_repo_refs(repo_id)
            return refs.branches[0].target_commit if refs.branches else None
        except Exception:
            return None

    # -- Transport API ------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        # spans nest inside the publisher's push.upload and inherit the
        # thread's correlation id (utils/obs.py); Hub latency is the
        # fleet's dominant phase, so it gets first-class attribution
        with obs.span("transport.publish_delta", miner=miner_id):
            repo = self.my_repo_id or miner_id
            return self._upload(repo, DELTA_FILE, delta)

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped) delta bytes."""
        repo = self.my_repo_id or miner_id
        return self._upload_bytes(repo, DELTA_FILE, data)

    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        with obs.span("transport.fetch_delta", miner=miner_id):
            return self._download(miner_id, DELTA_FILE, template)

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw bytes — multi-template validation (full vs LoRA wire formats)
        must not pay two LFS pulls per miner."""
        return self._download_bytes(miner_id, DELTA_FILE)

    def delta_revision(self, miner_id: str) -> Revision:
        return self._revision(miner_id)

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        from .base import encode_delta_meta
        repo = self.my_repo_id or miner_id
        self._upload_bytes(repo, META_FILE, encode_delta_meta(meta))

    # -- wire-v2 shards ------------------------------------------------------
    # The Hub's namespace is a repo per miner, so shards are FILES inside
    # the miner's own repo (shards/<layer>.msgpack) rather than reserved
    # top-level artifact ids — same per-layer overwrite semantics, and
    # the repo's history squash (gc) bounds their storage exactly like
    # the delta file's.
    def _shard_file(self, layer_key: str) -> str:
        from .base import shard_layer_slug
        return f"shards/{shard_layer_slug(layer_key)}.msgpack"

    def publish_shard(self, hotkey: str, layer_key: str,
                      data: bytes) -> None:
        repo = self.my_repo_id or hotkey
        self._upload_bytes(repo, self._shard_file(layer_key), data)

    def fetch_shard(self, hotkey: str, layer_key: str) -> bytes | None:
        return self._download_bytes(hotkey, self._shard_file(layer_key))

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        from .base import META_MAX_BYTES, parse_delta_meta
        return parse_delta_meta(self._download_bytes(
            miner_id, META_FILE, max_bytes=META_MAX_BYTES))

    # -- base-distribution shards/manifests (engine/basedist.py) -------------
    # Base shards and per-revision manifests are FILES inside the shared
    # averaged-model repo (base_shards/<layer>.msgpack,
    # base_manifests/<revision>.json) — per-layer overwrite semantics
    # for shards, per-revision append for manifests, both bounded by the
    # base repo's history squash like the base file itself.
    def publish_base_shard(self, layer_key: str, data: bytes) -> None:
        from .base import shard_layer_slug
        self._upload_bytes(self.base_repo_id,
                           f"base_shards/{shard_layer_slug(layer_key)}"
                           ".msgpack", data)

    def fetch_base_shard(self, layer_key: str) -> bytes | None:
        from .base import shard_layer_slug
        return self._download_bytes(
            self.base_repo_id,
            f"base_shards/{shard_layer_slug(layer_key)}.msgpack")

    def publish_base_manifest(self, revision: str, data: bytes) -> None:
        from .base import lineage_slug
        self._upload_bytes(self.base_repo_id,
                           f"base_manifests/{lineage_slug(revision)}.json",
                           data)

    def fetch_base_manifest(self, revision: str) -> bytes | None:
        from .base import BASE_MANIFEST_MAX_BYTES, lineage_slug
        return self._download_bytes(
            self.base_repo_id,
            f"base_manifests/{lineage_slug(revision)}.json",
            max_bytes=BASE_MANIFEST_MAX_BYTES)

    def _squash_base_repo(self) -> None:
        """Squash BEFORE publishing (reference order, hf_manager.py:73-136):
        squashing after would rewrite the just-returned commit SHA, so the
        averager's recorded revision would go stale and every peer that
        pulled in the publish->squash window would see a phantom revision
        change and reset a second time on identical bytes."""
        if self.owns_base_repo:
            try:
                self.api.super_squash_history(repo_id=self.base_repo_id)
            except Exception:
                pass  # best-effort, like the reference

    def publish_base(self, base: Params) -> Revision:
        with obs.span("transport.publish_base"):
            self._squash_base_repo()
            return self._upload(self.base_repo_id, BASE_FILE, base)

    def publish_base_raw(self, data: bytes) -> Revision:
        self._squash_base_repo()
        return self._upload_bytes(self.base_repo_id, BASE_FILE, data)

    def fetch_base_bytes(self) -> bytes | None:
        return self._download_bytes(self.base_repo_id, BASE_FILE)

    def fetch_base(self, template: Params):
        with obs.span("transport.fetch_base"):
            tree = self._download(self.base_repo_id, BASE_FILE, template)
            if tree is None:
                return None
            return tree, self._revision(self.base_repo_id)

    def base_revision(self) -> Revision:
        return self._revision(self.base_repo_id)

    def gc(self) -> None:
        """Squash history on this node's delta repo to bound Hub storage.
        The averaged-model repo is squashed on the publish path instead
        (_squash_base_repo) so the recorded base revision stays live."""
        if self.my_repo_id:
            try:
                self.api.super_squash_history(repo_id=self.my_repo_id)
            except Exception:
                pass  # GC is best-effort, like the reference's try/except
