"""Local-filesystem transport.

The reference's LocalHFManager (hf_manager.py:200-241) — a directory with
SHA-256 content-hash change detection — promoted to a first-class backend.
Multiple OS processes can run a full miner → validator → averager round
against one shared directory with no network, which is also how multi-node
topologies are exercised on a single box (SURVEY.md §4.1).

Layout:
    root/
      deltas/<miner_id>.msgpack        one artifact per miner, overwritten
      base/averaged_model.msgpack      the shared base model

Writes are atomic (tmp + rename, see serialization.save_file) so a reader
never sees a torn artifact — the reference has no such guarantee.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any

from .. import serialization as ser
from .. import signing
from ..utils import obs
from .base import (META_MAX_BYTES, Revision, encode_delta_meta,
                   parse_delta_meta)

Params = Any

_DELTA_FMT = "%s.msgpack"
_META_FMT = "%s.meta.json"
_BASE_NAME = "averaged_model.msgpack"

# roots of every transport constructed in this process — the conftest
# shard-hygiene guard scans them for leaked *.tmp files after each test
# module (a .tmp that outlives its publish means a write path skipped
# the atomic tmp+rename discipline or died between the two steps and
# nobody cleaned up). Paths, not objects: a root outliving its
# transport is exactly the case the guard wants to see.
_LIVE_ROOTS: set = set()


def live_roots() -> list[str]:
    """Roots of every LocalFSTransport this process has constructed that
    still exist on disk (test-hygiene introspection)."""
    return [r for r in sorted(_LIVE_ROOTS) if os.path.isdir(r)]


def _hash_file(path: str) -> Revision:
    if not os.path.exists(path):
        return None
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_atomic(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())  # rename must never commit ahead of the data
    os.replace(tmp, path)  # readers never see a torn artifact


def _read_capped(path: str, max_bytes: int) -> bytes | None:
    try:
        if os.path.getsize(path) > max_bytes:
            return None
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


class LocalFSTransport:
    def __init__(self, root: str, *, max_bytes: int = ser.DEFAULT_MAX_BYTES):
        self.root = root
        self.max_bytes = max_bytes
        # revision-probe cache: path -> ((mtime_ns, size, ino), sha256).
        # The ingest pool probes every miner's revision every round
        # (engine/ingest.py); without this each probe re-hashes the full
        # artifact — O(model bytes) of pure I/O per miner per round for
        # files that almost never changed. The stat signature includes
        # the inode because _write_atomic's rename always lands a fresh
        # one, so an overwrite inside mtime granularity still misses.
        self._rev_cache: dict[str, tuple[tuple, str]] = {}
        os.makedirs(os.path.join(root, "deltas"), exist_ok=True)
        os.makedirs(os.path.join(root, "base"), exist_ok=True)
        _LIVE_ROOTS.add(os.path.abspath(root))

    def _revision_of(self, path: str) -> Revision:
        try:
            st = os.stat(path)
        except OSError:
            return None
        sig = (st.st_mtime_ns, st.st_size, st.st_ino)
        hit = self._rev_cache.get(path)
        if hit is not None and hit[0] == sig:
            return hit[1]
        obs.count("transport.revision_hash")
        h = _hash_file(path)
        if h is not None:
            self._rev_cache[path] = (sig, h)
        return h

    @staticmethod
    def _safe_id(miner_id: str) -> str:
        """One sanitizer for every per-miner path: the artifact and its
        rider must always map to the SAME identity."""
        return miner_id.replace("/", "_").replace("..", "_")

    def _delta_path(self, miner_id: str) -> str:
        return os.path.join(self.root, "deltas",
                            _DELTA_FMT % self._safe_id(miner_id))

    @property
    def _base_path(self) -> str:
        return os.path.join(self.root, "base", _BASE_NAME)

    # -- miner side ---------------------------------------------------------
    def publish_delta(self, miner_id: str, delta: Params) -> Revision:
        # transport spans nest inside the caller's phase spans (e.g. the
        # publisher's push.upload) and inherit the thread's correlation id
        with obs.span("transport.publish_delta", miner=miner_id):
            path = self._delta_path(miner_id)
            ser.save_file(delta, path)
            return self._revision_of(path)

    def publish_raw(self, miner_id: str, data: bytes) -> Revision:
        """Arbitrary (possibly signature-enveloped, possibly hostile) bytes
        as a 'delta' — signed publishes and loadgen both land here."""
        path = self._delta_path(miner_id)
        _write_atomic(path, data)
        return self._revision_of(path)

    # -- validator / averager side -----------------------------------------
    def fetch_delta(self, miner_id: str, template: Params) -> Params | None:
        with obs.span("transport.fetch_delta", miner=miner_id):
            data = self.fetch_delta_bytes(miner_id)
            if data is None:
                return None
            try:
                # envelope-tolerant WITHOUT verification: an unsigned node
                # on a signed fleet still reads artifacts (verification
                # lives in SignedTransport, which uses the raw-bytes path)
                return ser.validated_load(signing.strip_envelope(data),
                                          template, max_bytes=self.max_bytes)
            except ser.PayloadError:
                return None

    def fetch_delta_bytes(self, miner_id: str) -> bytes | None:
        """Raw artifact bytes (size-capped), one read — for multi-template
        validation and for SignedTransport's verification."""
        return _read_capped(self._delta_path(miner_id), self.max_bytes)

    def delta_revision(self, miner_id: str) -> Revision:
        return self._revision_of(self._delta_path(miner_id))

    def _meta_path(self, miner_id: str) -> str:
        return os.path.join(self.root, "deltas",
                            _META_FMT % self._safe_id(miner_id))

    def publish_delta_meta(self, miner_id: str, meta: dict) -> None:
        _write_atomic(self._meta_path(miner_id), encode_delta_meta(meta))

    def fetch_delta_meta(self, miner_id: str) -> dict | None:
        return parse_delta_meta(
            _read_capped(self._meta_path(miner_id), META_MAX_BYTES))

    # -- base model ---------------------------------------------------------
    def publish_base(self, base: Params) -> Revision:
        with obs.span("transport.publish_base"):
            ser.save_file(base, self._base_path)
            return self._revision_of(self._base_path)

    def publish_base_raw(self, data: bytes) -> Revision:
        """Pre-serialized (possibly signature-enveloped) base bytes."""
        _write_atomic(self._base_path, data)
        return self._revision_of(self._base_path)

    def fetch_base_bytes(self) -> bytes | None:
        return _read_capped(self._base_path, self.max_bytes)

    def fetch_base(self, template: Params):
        with obs.span("transport.fetch_base"):
            data = self.fetch_base_bytes()
            if data is None:
                return None
            try:
                tree = ser.validated_load(signing.strip_envelope(data),
                                          template, max_bytes=self.max_bytes)
            except ser.PayloadError:
                # a torn/corrupt base reads as "absent", never a crash
                return None
            return tree, self._revision_of(self._base_path)

    def base_revision(self) -> Revision:
        return self._revision_of(self._base_path)

    def gc(self) -> None:
        pass  # overwrite-in-place layout never accumulates history
