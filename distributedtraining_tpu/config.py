"""Run configuration: dataclasses + a CLI builder, parsed only from main().

The reference merges bittensor arg groups with subnet args into one bt.config
namespace (hivetrain/config/config.py:44-60) and — worse — parses sys.argv at
module import time (training_manager.py:22-24), which SURVEY.md §1 flags as
the defect that makes the library unimportable without a chain. Here the
config is a plain dataclass; ``RunConfig.from_args`` is called explicitly by
the role entry points (neurons/) and never at import.

Flag parity map (reference → here):
  --netuid                      → --netuid           (base_subnet_config.py)
  --wallet.hotkey               → --hotkey
  --storage.my_repo_id          → --my-repo-id       (hivetrain_config.py:14)
  --storage.averaged_model_repo_id → --averaged-model-repo-id (:15)
  --storage.gradient_dir/model_dir → --work-dir      (:16-17)
  --batch_size                  → --batch-size       (:34-41)
  --neuron.epoch_length         → --epoch-length     (base_subnet_config.py:72)
  --neuron.vpermit_tao_limit    → --vpermit-stake-limit (:178-183)
  --mock                        → --backend local    (:79-84)
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass
class MeshSpec:
    """dp×fsdp×sp×tp axis sizes; 0 for dp means "all visible devices".
    dcn_dp > 1 lays the outermost dp groups across the slow network
    (multi-slice DCN) — see parallel.multihost.pod_mesh."""
    dp: int = 0
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    dcn_dp: int = 1
    auto: bool = False   # pick axes from model size (best_mesh_shape)


@dataclasses.dataclass
class RunConfig:
    role: str = "miner"                      # miner | validator | averager

    # -- identity / chain ---------------------------------------------------
    chain: str = "local"                     # local | bittensor
    netuid: int = 25                         # prod subnet (README.md:93)
    hotkey: str = "hotkey_0"
    wallet_name: str = "default"             # bittensor wallet (cold) name
    wallet_hotkey: str = "default"           # bittensor wallet hotkey name
    subtensor_network: str = "finney"        # bittensor network endpoint
    epoch_length: int = 100                  # blocks between weight sets
    vpermit_stake_limit: float = 1000.0
    allow_no_vpermit: bool = False           # run an unpermitted validator
    resync_blocks: int = 0                   # metagraph resync throttle

    # -- storage / transport ------------------------------------------------
    backend: str = "local"                   # local | memory | hf
    work_dir: str = "./hivetrain_run"
    my_repo_id: Optional[str] = None
    averaged_model_repo_id: Optional[str] = None

    # -- artifact authenticity (transport/signed.py) ------------------------
    sign_artifacts: bool = False             # Ed25519-envelope publishes
    wallet_path: Optional[str] = None        # default: <work_dir>/wallets/<hotkey>.json
    base_signer: Optional[str] = None        # hotkey expected to sign the base

    # -- model / optimization ----------------------------------------------
    model: str = "gpt2-124m"                 # gpt2/llama preset name
    init_from: Optional[str] = None          # pretrained weights (hf:<repo>,
                                             # dir, or .safetensors/.bin path)
    seq_len: int = 64                        # miner train len (miner.py:70)
    eval_seq_len: int = 512                  # validator len (validator.py:63)
    batch_size: int = 8
    eval_batches: int = 12                   # ~100 texts / batch 8 (ref :49,98)
    score_metric: str = "loss"               # loss | perplexity (ref :93-97)
    max_delta_abs: float = 1e3               # admission magnitude cap (0=off)
    accept_quant: bool = True                # accept int8-wire submissions
    stale_deltas: Optional[str] = None       # skip|accept (None = role default)
    learning_rate: float = 5e-4              # neurons/miner.py:121-128
    weight_decay: float = 0.01               # AdamW decoupled decay
    grad_clip: Optional[float] = None
    mu_dtype: Optional[str] = None           # "bfloat16": half-size Adam mu
    lora_rank: int = 0                       # >0: LoRA-delta mode (config 4)
    lora_alpha: float = 16.0
    dataset: str = "auto"                    # auto | wikitext | synthetic
    n_docs: int = 256                        # corpus cap fed to text_corpus
    tokenizer: str = "auto"                  # auto | byte | <hf name>
    fused_loss: bool = False                 # tiled-head CE (no [B,T,V] logits)
    scan_blocks: bool = False                # lax.scan the block stack
    logits_dtype: Optional[str] = None       # "bfloat16": half-size logits buf
    delta_dtype: Optional[str] = None        # bf16/int8/sparse8 wire deltas
    delta_density: float = 1.0 / 64.0        # sparse8 kept-coordinate ratio
    # wire v2 (ROADMAP item 1): sparse+quantized packed deltas published
    # as content-addressed per-layer shards + manifest (delta.pack_delta_v2,
    # serialization shard container, engine/publish.py uploads only
    # changed shards, engine/ingest.py fetches only changed shards)
    wire_v2: bool = False                    # miner: publish the v2 wire
    wire_density: float = 1.0 / 64.0         # v2 kept-coordinate ratio
    wire_quant: str = "int8"                 # v2 kept values: int8 | none
    accept_wire_v2: bool = True              # receivers: decode v2 manifests
    # content-addressed base distribution (engine/basedist.py): the
    # averager publishes hash-addressed per-layer base shards + a signed
    # per-revision manifest next to the monolithic base; fetchers
    # delta-pull only changed-hash layers, racing __mirror__ replicas
    # before the origin. The monolithic artifact stays the fallback, so
    # mixed old/new fleets interoperate with no flag day.
    base_wire_v2: bool = True                # sharded publish + delta-pull
    base_mirrors: str = ""                   # comma list of mirror nodes
    base_mirror: bool = True                 # sub-averagers: mirror duty
    base_store_mb: int = 1024                # local shard-store budget
    remat: Optional[bool] = None             # per-block rematerialization
    prefetch_depth: int = 2                  # host pipeline look-ahead (0=off)
    accum_steps: int = 1                     # microbatches per optimizer step
    # JAX persistent compilation cache (ROADMAP item 5): a directory all
    # roles point jax_compilation_cache_dir at, so a role RESTART (and
    # the PR-4 warm rounds) deserializes yesterday's XLA executables
    # instead of recompiling them — compile.ms then measures cache-load
    # time, not compile time. None disables (in-memory jit cache only).
    compile_cache_dir: Optional[str] = None

    # -- serving plane (engine/serve.py; neurons/server.py) -----------------
    serve_port: int = 0                      # HTTP /generate port (0 = off)
    serve_slots: int = 8                     # concurrent decode slots
    serve_page_size: int = 16                # KV-cache page, in tokens
    serve_kv_pages: int = 0                  # page-pool size (0 = auto)
    serve_max_new: int = 64                  # default max_new_tokens
    serve_max_seq: int = 0                   # cache len cap (0 = model max)
    serve_max_queue: int = 0                 # shed past this depth (0 = off)
    serve_prefix_cache: bool = True          # shared-prefix KV page reuse
    serve_speculative: bool = False          # draft-verify speculative decode
    serve_draft_k: int = 4                   # drafted tokens per slot/step
    serve_draft_repo: str = ""               # draft base: "preset@work_dir"
    serve_trace: bool = True                 # request-scoped stage traces
    serve_trace_exemplars: int = 4           # K slowest frozen per window
    serve_trace_window: float = 30.0         # exemplar window (seconds)
    serve_phase: str = "unified"             # unified | prefill | decode
    swap_policy: str = "drain"               # drain | restart
    swap_poll: float = 15.0                  # base-revision poll (seconds)

    # -- mesh ---------------------------------------------------------------
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)

    # -- multi-host (config 5); None = auto-detect from the environment -----
    multihost_coordinator: Optional[str] = None   # host:port of process 0
    multihost_processes: Optional[int] = None
    multihost_id: Optional[int] = None

    # -- cadences (seconds) -------------------------------------------------
    send_interval: float = 800.0             # miner.py:125
    # async publication pipeline (engine/publish.py): overlap the miner's
    # delta push / rider / checkpoint I/O with training compute; a push
    # still in flight at the next interval is superseded, never queued.
    # --no-push-async restores the fully sequential reference path.
    push_async: bool = True
    push_queue_depth: int = 1                # pending pushes before supersede
    check_update_interval: float = 300.0
    # miner self-validation guard: the miner scores its own candidate on
    # the held-out shard every ``self_eval_interval`` seconds and reverts
    # to its best-seen state after ``self_eval_patience`` non-improving
    # evals (engine/train.py MinerLoop._val_guard). -1 = follow
    # send_interval (default on); 0 disables (reference-parity blind
    # training, training_manager.py:380-392)
    self_eval_interval: float = -1.0
    self_eval_patience: int = 3
    self_eval_margin: float = 0.1
    keep_optimizer_on_pull: bool = False     # ref parity: reset on pull
    checkpoint_interval: float = 600.0       # 0 disables local checkpointing
    checkpoint_dir: Optional[str] = None     # default: <work_dir>/checkpoints/<hotkey>
    validation_interval: float = 1800.0      # validator.py:112
    val_cohort: int = 8                      # miners scored per batched eval
    #                                          pass (<=1 = sequential legacy)
    val_pipeline_depth: int = 1              # cohorts staged ahead of eval
    #                                          (0 disables fetch/eval overlap)
    averaging_interval: float = 1200.0       # averager.py:106
    # concurrent revision-aware ingest (engine/ingest.py, validator +
    # averager): fetch-pool width (1 = serial fetch order) and the
    # content-addressed host cache's byte budget (0 disables — every
    # round re-downloads every artifact, the reference's behavior)
    ingest_workers: int = 4
    ingest_cache_mb: int = 2048

    # -- averager strategy --------------------------------------------------
    strategy: str = "parameterized"          # weighted | parameterized | genetic
    publish_policy: str = "improved"         # improved | always (ref parity)
    merge_chunk: int = 8                     # weighted-merge device chunk
    meta_epochs: int = 7                     # averager.py:106
    genetic_population: int = 10             # averaging_logic.py:830-970
    genetic_generations: int = 10
    genetic_sigma: float = 0.1
    genetic_screen_batches: int = 2          # 0 = full-set fitness
    meta_lr: float = 0.01
    meta_optimizer: str = "adam"             # adam | sgd (ref spelling)
    outer_momentum: float = 0.0              # >0 wraps strategy in OuterOptMerge
    outer_lr: float = 0.7                    # DiLoCo-style outer Nesterov step

    # -- hierarchical aggregation (engine/hier_average.py) ------------------
    # --hier sub: this averager is a SUB-AVERAGER — it gathers its
    # plan_fanout slice of the metagraph and publishes the partial
    # aggregate under the reserved __agg__.<node> id instead of merging
    # the whole fleet. --hier root: gather the configured sub nodes'
    # aggregates (never the metagraph) and publish the base. "" = the
    # flat single-averager reference topology.
    hier: str = ""                           # "" | sub | root
    hier_node: str = ""                      # sub node id (default: hotkey)
    hier_nodes: str = ""                     # comma list of sub node ids
    hier_fanout: int = 0                     # auto-plan width when no list
    hier_wire_v2: bool = False               # aggregates ride the v2 wire

    # -- remediation / failover (engine/remediate.py) -----------------------
    # --remediate closes the loop from SLO breach to action on the
    # monitor roles: quarantine + probation for breaching miners, score
    # decay, and elastic cohort sizing over the compiled-bucket ladder.
    # Requires the health plane (--heartbeat-interval > 0) for breaches
    # to exist at all.
    remediate: bool = False
    quarantine_rules: str = "push_failure_streak,loss_divergence,stale_node"
    probation_beats: int = 3                 # clean beats to re-admit
    probation_rounds: int = 2                # rounds on probation after
    score_decay: float = 0.25                # per-round quarantined decay
    # averager failover: --standby starts a PASSIVE averager that follows
    # the primary's lease/heartbeat/base-revision and takes over
    # publication (lease epoch + 1) after --failover-deadline seconds of
    # silence (0 = 3x --averaging-interval). The primary holds the lease
    # whenever --remediate or --standby fleets are in play.
    standby: bool = False
    failover_deadline: float = 0.0

    # -- chaos injection (transport/chaos.py; soaks and tests only) ----------
    # JSON ChaosSpec wrapping this role's transport, e.g.
    # '{"fetch_error_rate": 0.1, "latency_s": 0.05, "seed": 7}' — faults
    # are deterministic per (seed, op sequence). Never set in production.
    chaos_spec: Optional[str] = None

    # -- bounded runs (tests / smoke) --------------------------------------
    max_steps: Optional[int] = None
    rounds: Optional[int] = None

    # -- observability ------------------------------------------------------
    metrics_path: Optional[str] = None       # JSONL sink
    # size-based JSONL rotation: rotate the --metrics-path file once it
    # passes this many MB, keeping the newest --metrics-keep-segments
    # rotated segments (0 = never rotate, the historical single-file
    # behavior; obs_report/fleet_report read rotated runs transparently)
    metrics_rotate_mb: int = 0
    metrics_keep_segments: int = 3
    log_every: int = 1000                    # train steps between metric logs
                                             # (ref :394-402)
    # fleet health plane (engine/health.py): >0 publishes a versioned
    # heartbeat through the transport every N seconds; the validator and
    # averager additionally run the FleetMonitor (contribution ledger +
    # SLO rules) over the fleet's heartbeats. 0 disables the plane.
    heartbeat_interval: float = 0.0
    # zero-dependency Prometheus-text exporter (utils/obs_http.py):
    # serve the obs registry (+ fleet ledger, where one exists) on
    # http://127.0.0.1:<port>/metrics — plus the postmortem debug
    # endpoints (/debug/dump, /debug/profile, /debug/stacks). 0 disables.
    obs_port: int = 0
    # flight recorder (utils/flight.py): bounded in-memory ring of
    # structured events (spans, SLO fires, lease flips, publish/swap
    # outcomes, heartbeats, sanitized config) frozen into a
    # content-addressed __pm__ postmortem bundle on SLO breach /
    # remediation action / crash, published through this role's
    # transport. Value = ring capacity in events; 0 disables the plane.
    flight_events: int = 512
    # device performance observatory (utils/devprof.py): per-program XLA
    # cost attribution (FLOPs/bytes), compile + execution histograms,
    # and roofline achieved-fraction gauges for every registered hot
    # path; exposed via obs_http dt_prog_* series, heartbeat anat.*
    # fields, and the {"devprof": ...} JSONL record perf_report joins.
    # On by default wherever a metrics sink is configured (measured
    # < 2% overhead, bench._time_devprof_overhead).
    devprof: bool = True
    # lineage/provenance plane (engine/lineage.py): the averager (and
    # every sub-averager) freezes a content-addressed __lineage__ record
    # per landed merge — parent revision, the exact contribution set and
    # weights — and runs the EWMA/CUSUM quality-drift detector over the
    # merged held-out loss. Records are KBs; measured < 2% at soak
    # cadence (bench._time_lineage_overhead).
    lineage: bool = True
    mlflow_uri: Optional[str] = None
    profile_dir: Optional[str] = None        # jax.profiler trace capture
    profile_steps: int = 5                   # train steps per capture
    # anomaly-triggered capture (utils/obs.AnomalyMonitor): a loss spike,
    # push-failure streak, or step-time p99 blowout arms ONE disarmed
    # TraceCapture automatically — profiler evidence of the first anomaly
    # lands on disk without anyone watching
    anomaly_trace: bool = True
    anomaly_dir: Optional[str] = None        # default: <work_dir>/anomaly_traces/<hotkey>

    @classmethod
    def from_args(cls, role: str, argv: Sequence[str] | None = None
                  ) -> "RunConfig":
        ns = build_parser(role).parse_args(argv)
        mesh = MeshSpec(dp=ns.dp, fsdp=ns.fsdp, sp=ns.sp, tp=ns.tp,
                        dcn_dp=ns.dcn_dp, auto=ns.mesh_auto)
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in vars(ns).items() if k in fields}
        kw.pop("mesh", None)
        return cls(role=role, mesh=mesh, **kw)


def _nonneg_float(value: str) -> float:
    f = float(value)
    if f < 0:
        raise argparse.ArgumentTypeError(
            f"{value}: must be >= 0 (0 disables)")
    return f


def _dataset_arg(value: str) -> str:
    if value in ("auto", "wikitext", "synthetic") or \
            value.startswith("files:"):
        return value
    raise argparse.ArgumentTypeError(
        f"{value!r}: expected auto, wikitext, synthetic, or files:<glob>")


def build_parser(role: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=f"neurons/{role}.py",
                                description=f"hivetrain-tpu {role}")
    d = RunConfig()

    g = p.add_argument_group("chain")
    g.add_argument("--chain", choices=("local", "bittensor"), default=d.chain,
                   help="local = JSON-file chain under --work-dir (single "
                        "box / tests); bittensor = substrate chain via the "
                        "bittensor SDK. A multi-host --backend hf deployment "
                        "needs --chain bittensor or every role sees only its "
                        "own local scores.")
    g.add_argument("--netuid", type=int, default=d.netuid)
    g.add_argument("--hotkey", default=d.hotkey)
    g.add_argument("--wallet-name", dest="wallet_name", default=d.wallet_name)
    g.add_argument("--wallet-hotkey", dest="wallet_hotkey",
                   default=d.wallet_hotkey)
    g.add_argument("--subtensor-network", dest="subtensor_network",
                   default=d.subtensor_network)
    g.add_argument("--epoch-length", dest="epoch_length", type=int,
                   default=d.epoch_length)
    g.add_argument("--resync-blocks", dest="resync_blocks", type=int,
                   default=d.resync_blocks,
                   help="serve the cached metagraph within this many blocks "
                        "of the last resync (0 = resync every sync call); "
                        "bittensor chain only")
    g.add_argument("--vpermit-stake-limit", dest="vpermit_stake_limit",
                   type=float, default=d.vpermit_stake_limit)
    if role == "validator":
        g.add_argument("--allow-no-vpermit", dest="allow_no_vpermit",
                       action="store_true",
                       help="run even when this hotkey holds no validator "
                            "stake (scores are computed but weights are "
                            "never emitted; useful for dry runs)")
        g.add_argument("--score-metric", dest="score_metric",
                       choices=("loss", "perplexity"),
                       default=d.score_metric,
                       help="scoring rule: max(0, base - candidate) on "
                            "eval loss or on perplexity (the reference's "
                            "two modes, validation_logic.py:93-97)")

    g = p.add_argument_group("storage")
    g.add_argument("--backend", choices=("local", "memory", "hf"),
                   default=d.backend)
    g.add_argument("--work-dir", dest="work_dir", default=d.work_dir)
    g.add_argument("--my-repo-id", dest="my_repo_id", default=None)
    g.add_argument("--averaged-model-repo-id", dest="averaged_model_repo_id",
                   default=None)
    g.add_argument("--sign-artifacts", dest="sign_artifacts",
                   action="store_true",
                   help="publish artifacts in Ed25519 signature envelopes "
                        "and verify peers' signatures against their "
                        "registered pubkeys (transport/signed.py)")
    g.add_argument("--wallet-path", dest="wallet_path", default=None,
                   help="identity keyfile for --sign-artifacts (created if "
                        "missing); default <work-dir>/wallets/<hotkey>.json")
    g.add_argument("--base-signer", dest="base_signer", default=None,
                   help="hotkey expected to sign the published base model "
                        "(the averager's); with a registered pubkey, base "
                        "fetches then REQUIRE a valid signature")
    g.add_argument("--base-wire-v2", dest="base_wire_v2",
                   action="store_true", default=d.base_wire_v2,
                   help="content-addressed sharded base distribution "
                        "(engine/basedist.py; default ON): the averager "
                        "publishes each base as hash-addressed per-layer "
                        "shards + a signed per-revision manifest NEXT TO "
                        "the monolithic artifact, and fetchers pull only "
                        "changed-hash layers (unchanged layer = 0 bytes), "
                        "racing any mirror that has the hash before the "
                        "origin. Mixed fleets need no flag day: the "
                        "monolithic base stays the fallback")
    g.add_argument("--no-base-wire-v2", dest="base_wire_v2",
                   action="store_false",
                   help="monolithic-only base distribution (the reference "
                        "posture): the averager publishes no shard plane "
                        "and fetchers never probe for manifests")
    g.add_argument("--base-mirrors", dest="base_mirrors",
                   default=d.base_mirrors,
                   help="comma list of mirror node ids this fetcher races "
                        "for base shards before the origin (normally the "
                        "fleet's __agg__ sub-averager nodes; the "
                        "averager's announce rider extends the list at "
                        "run time)")
    g.add_argument("--no-base-mirror", dest="base_mirror",
                   action="store_false", default=d.base_mirror,
                   help="sub-averagers only: do NOT re-publish base "
                        "shards under this node's __mirror__ slots")
    g.add_argument("--base-store-mb", dest="base_store_mb", type=int,
                   default=d.base_store_mb,
                   help="byte budget of the local content-addressed base "
                        "shard store (the delta-pull dedupe memory; 0 "
                        "disables caching — every sharded pull re-fetches "
                        "all layers)")

    g = p.add_argument_group("model")
    g.add_argument("--model", default=d.model)
    g.add_argument("--init-from", dest="init_from", default=None,
                   help="pretrained checkpoint to start from when no base "
                        "is published yet: hf:<repo_id> (local HF cache), a "
                        "checkpoint directory, or a .safetensors/.bin file "
                        "(the reference fine-tunes pretrained GPT-2, "
                        "neurons/miner.py:60)")
    g.add_argument("--seq-len", dest="seq_len", type=int, default=d.seq_len)
    g.add_argument("--eval-seq-len", dest="eval_seq_len", type=int,
                   default=d.eval_seq_len)
    g.add_argument("--batch-size", dest="batch_size", type=int,
                   default=d.batch_size)
    g.add_argument("--eval-batches", dest="eval_batches", type=int,
                   default=d.eval_batches)
    if role in ("validator", "averager"):
        g.add_argument("--max-delta-abs", dest="max_delta_abs",
                       type=_nonneg_float, default=d.max_delta_abs,
                       help="admission screen: reject submissions whose "
                            "largest |value| exceeds this (crude poisoning "
                            "guard the reference lacks; 0 disables)")
        g.add_argument("--no-accept-quant", dest="accept_quant",
                       action="store_false", default=d.accept_quant,
                       help="fleet is known all-float: reject int8-wire "
                            "submissions instead of dequantizing, and skip "
                            "the quant-template alloc on garbage")
        g.add_argument("--no-wire-v2", dest="accept_wire_v2",
                       action="store_false", default=d.accept_wire_v2,
                       help="refuse v2 shard-manifest submissions (the "
                            "v1-only receiver posture); v2 miners then "
                            "stage as no_delta")
        g.add_argument("--stale-deltas", dest="stale_deltas",
                       choices=("skip", "accept"), default=d.stale_deltas,
                       help="submissions whose rider names a superseded "
                            "base: 'skip' refuses them (averager default "
                            "— merging one re-adds the previous merge's "
                            "update on top of itself), 'accept' is the "
                            "reference's behavior (validator default). "
                            "Riderless submissions are always accepted")
    g.add_argument("--learning-rate", dest="learning_rate", type=float,
                   default=d.learning_rate)
    g.add_argument("--weight-decay", dest="weight_decay", type=float,
                   default=d.weight_decay,
                   help="AdamW decoupled weight decay")
    g.add_argument("--grad-clip", dest="grad_clip", type=float, default=None)
    g.add_argument("--mu-dtype", dest="mu_dtype",
                   choices=("float32", "bfloat16"), default=d.mu_dtype,
                   help="AdamW first-moment storage dtype; bfloat16 halves "
                        "its HBM footprint (7B/8B configs) at ~no "
                        "throughput cost (scripts/opt_dtype_probe.py)")
    g.add_argument("--lora-rank", dest="lora_rank", type=int,
                   default=d.lora_rank,
                   help=">0 switches the miner to LoRA-delta training; "
                        "validator/averager accept adapter submissions")
    g.add_argument("--lora-alpha", dest="lora_alpha", type=float,
                   default=d.lora_alpha)
    g.add_argument("--dataset", default=d.dataset, type=_dataset_arg,
                   help="auto | wikitext | synthetic | files:<glob> (local "
                        "text files as the corpus; real data with zero "
                        "egress)")
    g.add_argument("--n-docs", dest="n_docs", type=int, default=d.n_docs,
                   help="document cap for the corpus loader (train split; "
                        "runway for long soaks)")
    g.add_argument("--tokenizer", default=d.tokenizer,
                   help="auto | byte | word (corpus-fit word vocab, "
                        "deterministic per corpus) | bpe (byte-level BPE "
                        "trained locally on the machine's own text — the "
                        "32k real-vocab tokenizer, data/bpe.py) | "
                        "<hf tokenizer name>")
    g.add_argument("--fused-loss", dest="fused_loss", action="store_true",
                   help="compute the LM loss with a tiled head matmul that "
                        "never materializes the [batch, seq, vocab] logits "
                        "(HBM saver; GPT-2, Llama, and LoRA-delta mode)")
    g.add_argument("--accum-steps", dest="accum_steps", type=int,
                   default=d.accum_steps,
                   help="gradient-accumulation microbatches per optimizer "
                        "step (activation memory of batch/N at the same "
                        "effective batch; 7B/8B configs)")
    g.add_argument("--prefetch-depth", dest="prefetch_depth", type=int,
                   default=d.prefetch_depth,
                   help="batches the background input thread keeps ready "
                        "(tokenize+pack ahead of the device; 0 disables, "
                        "the reference's DataLoader-workers equivalent)")
    if role == "miner":  # only the miner publishes raw deltas
        g.add_argument("--delta-dtype", dest="delta_dtype",
                       choices=("float32", "bfloat16", "int8", "sparse8"),
                       default=d.delta_dtype,
                       help="wire dtype of published deltas: bfloat16 "
                            "halves artifact bytes; int8 quarters them "
                            "(per-tensor symmetric scales, rounding error "
                            "<= 1 step per artifact); sparse8 keeps only "
                            "the top-k |values| per tensor int8-quantized "
                            "(~2%% of f32 bytes at the default "
                            "--delta-density — the 7B/8B-config format; "
                            "needs a raw-bytes transport, which all "
                            "built-ins are). Receivers auto-detect every "
                            "form and dequantize at ingest; merges "
                            "accumulate in f32")
        g.add_argument("--delta-density", dest="delta_density", type=float,
                       default=d.delta_density,
                       help="sparse8 kept-coordinate ratio per tensor "
                            "(default 1/64; small tensors <= 4096 elements "
                            "always ship dense)")
        g.add_argument("--wire-v2", dest="wire_v2", action="store_true",
                       default=d.wire_v2,
                       help="publish deltas on the v2 shard-addressed "
                            "wire: top-k + quantized packed per-layer "
                            "form, split into content-addressed shards + "
                            "a small manifest — only CHANGED shards "
                            "upload each push, receivers fetch only "
                            "changed shards, and a miner-side "
                            "error-feedback residual keeps repeated "
                            "lossy publishes from drifting. Receivers "
                            "negotiate v1 fallback via the delta META "
                            "rider, so mixed fleets keep working")
        g.add_argument("--wire-density", dest="wire_density", type=float,
                       default=d.wire_density,
                       help="v2 kept-coordinate ratio per wire tensor "
                            "(default 1/64; tensors <= 4096 elements "
                            "ship dense)")
        g.add_argument("--wire-quant", dest="wire_quant",
                       choices=("int8", "none"), default=d.wire_quant,
                       help="v2 kept-value encoding: int8 (per-tensor "
                            "symmetric scale, 5 bytes/coordinate) or "
                            "none (f32 kept values, 8 bytes/coordinate, "
                            "zero quantization error)")
    g.add_argument("--logits-dtype", dest="logits_dtype",
                   choices=("float32", "bfloat16"), default=d.logits_dtype,
                   help="storage dtype of the [batch, seq, vocab] logits "
                        "buffer (the step's largest activation); MXU "
                        "accumulation stays f32 either way, the loss still "
                        "reduces in f32. bfloat16 halves its HBM round-trips")
    g.add_argument("--remat", dest="remat", action="store_true",
                   default=None,
                   help="jax.checkpoint each transformer block: activation "
                        "HBM of one block instead of the whole stack, one "
                        "extra forward of FLOPs (the 7B/8B configs' knob; "
                        "Llama presets default on, GPT-2 off)")
    g.add_argument("--no-remat", dest="remat", action="store_false",
                   help="force rematerialization OFF (overrides a preset "
                        "that defaults on)")
    g.add_argument("--scan-blocks", dest="scan_blocks", action="store_true",
                   help="trace the transformer stack as one lax.scan'd "
                        "block (~n_layer-fold smaller program, much faster "
                        "XLA compiles on deep models); identical math. "
                        "Wire artifacts (bases, deltas, adapters) stay in "
                        "the universal unrolled layout, so roles can flip "
                        "this independently")
    g.add_argument("--compile-cache-dir", dest="compile_cache_dir",
                   default=d.compile_cache_dir,
                   help="JAX persistent compilation cache directory "
                        "(created if missing): role restarts deserialize "
                        "previously-compiled XLA executables instead of "
                        "recompiling — point every role of a deployment "
                        "at the same path. Unset = in-memory jit cache "
                        "only (every restart recompiles)")

    if role == "server":
        g = p.add_argument_group("serving")
        g.add_argument("--serve-port", dest="serve_port", type=int,
                       default=d.serve_port,
                       help="HTTP generation frontend on "
                            "127.0.0.1:<port>/generate (0 = no HTTP; the "
                            "engine still serves in-process submits)")
        g.add_argument("--serve-slots", dest="serve_slots", type=int,
                       default=d.serve_slots,
                       help="concurrent decode slots (the continuous "
                            "batch width; slot-count buckets ride a "
                            "power-of-two compile ladder)")
        g.add_argument("--page-size", dest="serve_page_size", type=int,
                       default=d.serve_page_size,
                       help="KV-cache page size in tokens (the paging "
                            "granule: sequences own pages, not a "
                            "max-length stripe)")
        g.add_argument("--kv-pages", dest="serve_kv_pages", type=int,
                       default=d.serve_kv_pages,
                       help="total pages in the KV pool (0 = auto: "
                            "slots x pages-per-max-sequence + trash "
                            "page). Undersize deliberately to exercise "
                            "preemption")
        g.add_argument("--max-new-tokens", dest="serve_max_new", type=int,
                       default=d.serve_max_new,
                       help="default generation budget when a request "
                            "does not specify one")
        g.add_argument("--max-seq-len", dest="serve_max_seq", type=int,
                       default=d.serve_max_seq,
                       help="cache capacity per sequence in tokens "
                            "(0 = the model's position cap; rounded "
                            "down to a page multiple)")
        g.add_argument("--max-queue", dest="serve_max_queue", type=int,
                       default=d.serve_max_queue,
                       help="admission bound: past this queue depth the "
                            "HTTP frontend sheds with 429 + Retry-After "
                            "instead of queueing into the latency knee "
                            "(0 = queue without bound)")
        g.add_argument("--no-prefix-cache", dest="serve_prefix_cache",
                       action="store_false",
                       default=d.serve_prefix_cache,
                       help="disable shared-prefix KV page reuse "
                            "(refcounted pages + copy-on-write; on by "
                            "default — common system prompts prefill "
                            "once per server, not once per request)")
        g.add_argument("--speculative", dest="serve_speculative",
                       action="store_true",
                       default=d.serve_speculative,
                       help="speculative decoding: a small fleet-trained "
                            "draft proposes --draft-k tokens per slot per "
                            "step and one batched verify pass scores them "
                            "(provably lossless — output is bit-identical "
                            "to plain decode; off by default)")
        g.add_argument("--no-speculative", dest="serve_speculative",
                       action="store_false",
                       help="force speculative decoding off")
        g.add_argument("--draft-k", dest="serve_draft_k", type=int,
                       default=d.serve_draft_k,
                       help="drafted tokens per slot per speculative "
                            "step (tokens per verify ≈ 1 + accept_rate·K)")
        g.add_argument("--draft-repo", dest="serve_draft_repo",
                       default=d.serve_draft_repo,
                       help="draft base source as 'preset@work_dir' — a "
                            "second transport watching that deployment's "
                            "fleet-averaged revisions feeds the drafter's "
                            "hot-swap lane (empty: self-draft from the "
                            "serving transport, only useful for smoke "
                            "tests)")
        g.add_argument("--no-serve-trace", dest="serve_trace",
                       action="store_false", default=d.serve_trace,
                       help="disable request-scoped stage traces "
                            "(utils/reqtrace.py: per-request lifecycle "
                            "timelines, tail-exemplar freezes into the "
                            "flight recorder, SLO burn-rate feed; on by "
                            "default — host-side only, <2%% overhead)")
        g.add_argument("--trace-exemplars", dest="serve_trace_exemplars",
                       type=int, default=d.serve_trace_exemplars,
                       help="K slowest ttft/tpot requests whose full "
                            "timelines freeze per trace window")
        g.add_argument("--trace-window", dest="serve_trace_window",
                       type=_nonneg_float, default=d.serve_trace_window,
                       help="tail-exemplar reservoir window, seconds")
        g.add_argument("--serve-phase", dest="serve_phase",
                       choices=("unified", "prefill", "decode"),
                       default=d.serve_phase,
                       help="worker class for disaggregated serving "
                            "(engine/kv_transfer.py): 'prefill' runs "
                            "prompt prefill and exports KV pages as "
                            "content-addressed shards, 'decode' adopts "
                            "exported pages and decodes flat-out, "
                            "'unified' (default) does both — the "
                            "router learns the class from /healthz and "
                            "falls back to unified workers whenever a "
                            "class is missing or unhealthy")
        g.add_argument("--swap-policy", dest="swap_policy",
                       choices=("drain", "restart"),
                       default=d.swap_policy,
                       help="base hot-swap policy: 'drain' finishes "
                            "in-flight sequences on the revision they "
                            "started on (admission pauses), 'restart' "
                            "swaps immediately and requeues in-flight "
                            "prompts on the new revision")
        g.add_argument("--swap-poll", dest="swap_poll",
                       type=_nonneg_float, default=d.swap_poll,
                       help="seconds between base-revision probes on "
                            "the watcher thread")

    g = p.add_argument_group("mesh")
    g.add_argument("--dp", type=int, default=d.mesh.dp,
                   help="data-parallel axis; 0 = all visible devices")
    g.add_argument("--fsdp", type=int, default=d.mesh.fsdp)
    g.add_argument("--sp", type=int, default=d.mesh.sp)
    g.add_argument("--tp", type=int, default=d.mesh.tp)
    g.add_argument("--mesh-auto", dest="mesh_auto", action="store_true",
                   help="ignore --dp/--fsdp/--sp/--tp and pick the mesh "
                        "from the model size (dp while the Adam state fits "
                        "replicated, fsdp/tp as it grows)")
    g.add_argument("--dcn-dp", dest="dcn_dp", type=int, default=d.mesh.dcn_dp,
                   help="outermost dp groups that cross the slow network "
                        "(multi-slice DCN); keeps fsdp/sp/tp and the rest "
                        "of dp on ICI")
    g.add_argument("--multihost-coordinator", dest="multihost_coordinator",
                   default=None, metavar="HOST:PORT",
                   help="explicit jax.distributed coordinator for manual "
                        "(non-GCE) topologies; TPU pods auto-detect")
    g.add_argument("--multihost-processes", dest="multihost_processes",
                   type=int, default=None)
    g.add_argument("--multihost-id", dest="multihost_id", type=int,
                   default=None)

    g = p.add_argument_group("cadence")
    g.add_argument("--send-interval", dest="send_interval", type=float,
                   default=d.send_interval)
    if role == "miner":  # only the miner runs the publication pipeline
        g.add_argument("--push-async", dest="push_async",
                       action="store_true", default=d.push_async,
                       help="overlap delta publication (device->host "
                            "transfer, serialization, upload, meta rider) "
                            "and checkpoint I/O with training compute on a "
                            "background worker; an in-flight push is "
                            "superseded by the next interval's, never "
                            "queued behind (default on)")
        g.add_argument("--no-push-async", dest="push_async",
                       action="store_false",
                       help="restore the fully sequential publish path "
                            "(the reference's blocking upload semantics)")
        g.add_argument("--push-queue-depth", dest="push_queue_depth",
                       type=int, default=d.push_queue_depth,
                       help="pushes the publisher may hold pending before "
                            "the oldest is superseded (each artifact is "
                            "the whole cumulative delta, so >1 only delays "
                            "supersession; default 1)")
    g.add_argument("--self-eval-interval", dest="self_eval_interval",
                   type=float, default=d.self_eval_interval,
                   help="miner self-validation cadence in seconds; -1 = "
                        "follow --send-interval, 0 = disable the guard")
    g.add_argument("--self-eval-patience", dest="self_eval_patience",
                   type=int, default=d.self_eval_patience)
    g.add_argument("--self-eval-margin", dest="self_eval_margin",
                   type=float, default=d.self_eval_margin,
                   help="held-out loss may exceed the best-seen by this "
                        "much before an eval counts as a strike")
    g.add_argument("--keep-optimizer-on-pull",
                   dest="keep_optimizer_on_pull", action="store_true",
                   default=d.keep_optimizer_on_pull,
                   help="carry Adam moments across base pulls instead of "
                        "the reference's reset — removes the per-pull "
                        "warmup transient on short merge cadences")
    if role == "miner":  # only the miner wires a CheckpointStore today
        g.add_argument("--checkpoint-interval", dest="checkpoint_interval",
                       type=float, default=d.checkpoint_interval,
                       help="seconds between local Orbax checkpoints; "
                            "0 disables")
        g.add_argument("--checkpoint-dir", dest="checkpoint_dir",
                       default=None,
                       help="default: <work_dir>/checkpoints/<hotkey>")
    g.add_argument("--check-update-interval", dest="check_update_interval",
                   type=float, default=d.check_update_interval)
    g.add_argument("--validation-interval", dest="validation_interval",
                   type=float, default=d.validation_interval)
    g.add_argument("--val-cohort", dest="val_cohort", type=int,
                   default=d.val_cohort,
                   help="miner deltas scored per batched eval pass "
                        "(engine/batched_eval.py); <=1 restores the "
                        "sequential per-miner path")
    g.add_argument("--val-pipeline-depth", dest="val_pipeline_depth",
                   type=int, default=d.val_pipeline_depth,
                   help="cohorts staged (fetched+screened) ahead of device "
                        "eval; 0 disables the fetch/eval overlap")
    g.add_argument("--averaging-interval", dest="averaging_interval",
                   type=float, default=d.averaging_interval)
    if role in ("validator", "averager"):  # the delta-consuming roles
        g = p.add_argument_group("ingest")
        g.add_argument("--ingest-workers", dest="ingest_workers", type=int,
                       default=d.ingest_workers,
                       help="concurrent artifact fetches during delta "
                            "ingest (engine/ingest.py); 1 restores serial "
                            "fetch order")
        g.add_argument("--ingest-cache-mb", dest="ingest_cache_mb",
                       type=int, default=d.ingest_cache_mb,
                       help="byte budget (MB) of the content-addressed "
                            "host cache keyed (hotkey, delta_revision): "
                            "unchanged submissions skip download + decode "
                            "+ dequantize + screen entirely; 0 disables "
                            "(re-download every round, reference behavior)")

    if role == "averager":
        g = p.add_argument_group("strategy")
        g.add_argument("--strategy",
                       choices=("weighted", "parameterized", "genetic"),
                       default=d.strategy)
        g.add_argument("--merge-chunk", dest="merge_chunk", type=int,
                       default=d.merge_chunk,
                       help="deltas stacked on-device at a time in the "
                            "weighted merge (device memory stays "
                            "chunk x params however many miners submit)")
        g.add_argument("--meta-epochs", dest="meta_epochs", type=int,
                       default=d.meta_epochs)
        g.add_argument("--outer-momentum", dest="outer_momentum", type=float,
                       default=d.outer_momentum,
                       help=">0 applies a DiLoCo-style outer Nesterov "
                            "momentum step over the merged delta")
        g.add_argument("--outer-lr", dest="outer_lr", type=float,
                       default=d.outer_lr)
        g.add_argument("--meta-lr", dest="meta_lr", type=float,
                       default=d.meta_lr)
        g.add_argument("--meta-optimizer", dest="meta_optimizer",
                       choices=("adam", "sgd"), default=d.meta_optimizer,
                       help="meta-learning optimizer for the merge "
                            "weights; sgd is the reference's spelling, "
                            "adam actually separates the weights")
        g.add_argument("--genetic-population", dest="genetic_population",
                       type=int, default=d.genetic_population)
        g.add_argument("--genetic-generations", dest="genetic_generations",
                       type=int, default=d.genetic_generations)
        g.add_argument("--publish-policy", dest="publish_policy",
                       choices=("improved", "always"),
                       default=d.publish_policy,
                       help="'improved' (default) publishes the merged "
                            "base only when it does not worsen the current "
                            "base's eval loss (one extra eval pass; keeps "
                            "the shared base monotone under noisy/short "
                            "miner deltas); 'always' is the reference's "
                            "publish-regardless behavior")
        g.add_argument("--genetic-screen-batches",
                       dest="genetic_screen_batches", type=int,
                       default=d.genetic_screen_batches,
                       help="successive-halving fitness: rank candidates "
                            "on this many val batches, full passes only "
                            "for elites (0 = the reference's full-set "
                            "fitness for every candidate)")
        g.add_argument("--genetic-sigma", dest="genetic_sigma", type=float,
                       default=d.genetic_sigma)

        g = p.add_argument_group("hierarchy")
        g.add_argument("--hier", choices=("", "sub", "root"),
                       default=d.hier,
                       help="tree aggregation (engine/hier_average.py): "
                            "'sub' gathers a plan_fanout slice of the "
                            "fleet and publishes its partial aggregate "
                            "under __agg__.<node>; 'root' merges the "
                            "configured sub nodes' aggregates into the "
                            "base; '' is the flat reference topology")
        g.add_argument("--hier-node", dest="hier_node", default=d.hier_node,
                       help="this sub-averager's stable node id "
                            "(default: --hotkey); names its __agg__ "
                            "artifact and its subavg.<node> lease")
        g.add_argument("--hier-nodes", dest="hier_nodes",
                       default=d.hier_nodes,
                       help="comma-separated sub node ids — the root's "
                            "gather list AND every sub's shared "
                            "plan_fanout keyspace (the stable production "
                            "spelling)")
        g.add_argument("--hier-fanout", dest="hier_fanout", type=int,
                       default=d.hier_fanout,
                       help="miners per sub-averager when no --hier-nodes "
                            "list is given: nodes auto-name "
                            "sub0..subN-1, N = ceil(miners / fanout)")
        g.add_argument("--hier-wire-v2", dest="hier_wire_v2",
                       action="store_true", default=d.hier_wire_v2,
                       help="publish partial aggregates on the v2 shard "
                            "wire (density 1.0 + quant none — lossless; "
                            "unchanged aggregate layers dedupe at shard "
                            "granularity)")

    g = p.add_argument_group("resilience")
    if role in ("validator", "averager"):  # the monitor roles act on SLOs
        g.add_argument("--remediate", dest="remediate", action="store_true",
                       default=d.remediate,
                       help="act on SLO breaches (engine/remediate.py): "
                            "quarantine breaching miners out of the ingest "
                            "set (probation re-admission after clean "
                            "heartbeats), decay their scores, and size "
                            "cohorts down the compiled-bucket ladder; "
                            "needs --heartbeat-interval > 0")
        g.add_argument("--quarantine-rules", dest="quarantine_rules",
                       default=d.quarantine_rules,
                       help="comma-separated SLO rule NAMES whose breach "
                            "quarantines a miner")
        g.add_argument("--probation-beats", dest="probation_beats",
                       type=int, default=d.probation_beats,
                       help="fresh clean heartbeats before a quarantined "
                            "miner re-admits into probation")
        g.add_argument("--probation-rounds", dest="probation_rounds",
                       type=int, default=d.probation_rounds,
                       help="rounds a re-admitted miner stays on "
                            "probation (a breach there re-quarantines)")
        g.add_argument("--score-decay", dest="score_decay", type=float,
                       default=d.score_decay,
                       help="multiplier applied to a quarantined miner's "
                            "score each round")
    if role == "averager":
        g.add_argument("--standby", dest="standby", action="store_true",
                       default=d.standby,
                       help="start as a PASSIVE failover averager: follow "
                            "the primary's lease/heartbeat/base revision "
                            "and take over publication (lease epoch + 1) "
                            "only after --failover-deadline of silence")
        g.add_argument("--failover-deadline", dest="failover_deadline",
                       type=_nonneg_float, default=d.failover_deadline,
                       help="seconds of primary silence before a standby "
                            "takes over (0 = 3x --averaging-interval)")
    g.add_argument("--chaos-spec", dest="chaos_spec", default=None,
                   help="JSON transport/chaos.py ChaosSpec wrapping this "
                        "role's transport (deterministic fault injection "
                        "for soaks/tests; NEVER set in production), e.g. "
                        "'{\"fetch_error_rate\": 0.1, \"seed\": 7}'")

    g = p.add_argument_group("run bounds")
    g.add_argument("--max-steps", dest="max_steps", type=int, default=None)
    g.add_argument("--rounds", type=int, default=None)

    g = p.add_argument_group("observability")
    g.add_argument("--metrics-path", dest="metrics_path", default=None)
    g.add_argument("--metrics-rotate-mb", dest="metrics_rotate_mb",
                   type=int, default=d.metrics_rotate_mb,
                   help="rotate the --metrics-path JSONL once it exceeds "
                        "this many MB (0 = never; soak runs otherwise grow "
                        "one multi-GB file). obs_report/fleet_report read "
                        "rotated segments transparently")
    g.add_argument("--metrics-keep-segments", dest="metrics_keep_segments",
                   type=int, default=d.metrics_keep_segments,
                   help="rotated segments kept per metrics file")
    g.add_argument("--heartbeat-interval", dest="heartbeat_interval",
                   type=_nonneg_float, default=d.heartbeat_interval,
                   help="fleet health plane (engine/health.py): publish a "
                        "versioned heartbeat through the transport every N "
                        "seconds; validator/averager also aggregate the "
                        "fleet's heartbeats into the contribution ledger "
                        "and evaluate SLO rules. 0 disables")
    g.add_argument("--obs-port", dest="obs_port", type=int,
                   default=d.obs_port,
                   help="serve Prometheus-text metrics (obs registry + "
                        "fleet ledger) on 127.0.0.1:<port>/metrics, plus "
                        "the /debug/dump, /debug/profile?ms=N and "
                        "/debug/stacks postmortem endpoints; 0 disables")
    g.add_argument("--no-devprof", dest="devprof", action="store_false",
                   default=d.devprof,
                   help="disable the device performance observatory "
                        "(utils/devprof.py): per-program FLOPs/bytes "
                        "cost attribution, exec histograms, and roofline "
                        "achieved-fraction gauges")
    g.add_argument("--no-lineage", dest="lineage", action="store_false",
                   default=d.lineage,
                   help="disable the provenance plane (engine/lineage"
                        ".py): per-merge content-addressed __lineage__ "
                        "records (parent revision + exact contribution "
                        "set and weights, replay-auditable via "
                        "scripts/lineage_report.py) and the merged-"
                        "quality EWMA/CUSUM drift detector")
    g.add_argument("--flight-events", dest="flight_events", type=int,
                   default=d.flight_events,
                   help="flight-recorder ring capacity (utils/flight.py): "
                        "recent spans/SLO fires/lease flips/publish "
                        "outcomes kept in memory and frozen into a "
                        "transport-published __pm__ postmortem bundle on "
                        "SLO breach, remediation, or crash; 0 disables")
    if role == "miner":
        g.add_argument("--log-every", dest="log_every", type=int,
                       default=d.log_every,
                       help="train steps between metric-sink logs (each log "
                            "syncs the device loss to the host)")
    g.add_argument("--mlflow-uri", dest="mlflow_uri", default=None)
    if role == "miner":  # only the miner's train loop ticks TraceCapture
        g.add_argument("--profile-dir", dest="profile_dir", default=None,
                       help="capture a jax.profiler trace of a few "
                            "post-warmup train steps into this directory "
                            "(TensorBoard/xprof-readable), then continue "
                            "at full speed")
        g.add_argument("--profile-steps", dest="profile_steps", type=int,
                       default=d.profile_steps)
        g.add_argument("--no-anomaly-trace", dest="anomaly_trace",
                       action="store_false", default=d.anomaly_trace,
                       help="disable the anomaly-armed profiler capture "
                            "(a loss spike, push-failure streak, or "
                            "step-time p99 blowout otherwise records one "
                            "bounded jax.profiler trace automatically)")
        g.add_argument("--anomaly-dir", dest="anomaly_dir", default=None,
                       help="trace directory for the anomaly capture; "
                            "default <work-dir>/anomaly_traces/<hotkey>")
    return p
