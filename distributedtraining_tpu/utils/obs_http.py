"""Zero-dependency Prometheus-text HTTP exporter for the obs registry and
the fleet health ledger.

``--obs-port N`` makes any role scrapeable: a stdlib
``ThreadingHTTPServer`` on a daemon thread serves

- ``/metrics`` — Prometheus text exposition (version 0.0.4): every
  registry counter/gauge as ``dt_<name>`` (dots become underscores,
  the registry lint guarantees the rest is legal), every histogram as
  its flattened ``_count/_sum/_p50/_p95/_p99`` gauges, and — when a
  :class:`~..engine.health.FleetMonitor` is attached — the live
  contribution ledger as ``dt_fleet_*{role=...,hotkey=...}`` series
  (label cardinality is bounded by the fleet size, the same reasoning
  as the validator's one-structured-record rule).
- ``/healthz`` — a JSON liveness probe (role, metric count, fleet size).
- ``/debug/dump`` — freeze the flight recorder's ring (utils/flight.py)
  into a postmortem bundle NOW and return it as JSON (``?publish=1``
  also ships it through the Transport under the reserved ``__pm__`` id);
- ``/debug/profile?ms=N`` — capture N milliseconds of ``jax.profiler``
  trace into the exporter's profile dir (409 while one is running);
- ``/debug/stacks`` — an all-thread stack dump (text/plain), the
  wedged-loop question answered without gdb.

No new dependencies, no TLS, binds 127.0.0.1 by default — this is a
scrape endpoint for a co-located agent, not a public surface. Live
exporters are tracked in a weak set so the tests/conftest.py hygiene
guard can fail any test that leaves a socket listening (live profiler
sessions have their own guard via flight.live_profile_sessions).
"""

from __future__ import annotations

import json
import logging
import math
import sys
import threading
import traceback
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from . import devprof, obs

logger = logging.getLogger(__name__)

_LIVE_EXPORTERS: "weakref.WeakSet[ObsHTTPExporter]" = weakref.WeakSet()


def live_exporters() -> list["ObsHTTPExporter"]:
    return list(_LIVE_EXPORTERS)


def prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name. The registry lint
    ([a-z0-9_.]+) plus the ``dt_`` namespace prefix guarantees the result
    matches Prometheus's [a-zA-Z_][a-zA-Z0-9_]*."""
    return "dt_" + name.replace(".", "_")


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _label_escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
                 .replace("\n", r"\n")


# ledger field -> (prometheus suffix, help) — the numeric per-node series
_FLEET_SERIES = (
    ("beats", "fleet_beats", "distinct heartbeats observed"),
    ("last_seen_age_s", "fleet_last_seen_age_seconds",
     "seconds since the last fresh heartbeat"),
    ("steps", "fleet_steps", "lifetime steps reported"),
    ("step_rate", "fleet_step_rate", "steps per second"),
    ("loss_ema", "fleet_loss_ema", "node loss EMA"),
    ("pushes", "fleet_pushes", "deltas the node reports published"),
    ("pushes_failed", "fleet_pushes_failed", "exhausted publish retries"),
    ("published", "fleet_published", "distinct delta revisions staged"),
    ("accepted", "fleet_accepted", "deltas accepted into merges"),
    ("declined", "fleet_declined", "deltas declined at staging"),
    ("stale_rounds", "fleet_stale_rounds",
     "rounds since the delta revision changed"),
    ("score", "fleet_score", "latest validator score"),
    ("credit", "lineage_credit",
     "accumulated leave-one-out improvement credit across base "
     "revisions (engine/lineage.py)"),
    ("mem_peak_bytes", "fleet_mem_peak_bytes",
     "node device-memory high-water mark"),
    ("quarantined", "fleet_quarantined",
     "1 while the node is quarantined out of the ingest set"),
    ("probation", "fleet_probation",
     "1 while the node is re-admitted on probation"),
    ("kv_exported", "serve_kv_exported",
     "requests whose prefill KV pages were published for a decode "
     "worker (disaggregated serving, prefill phase)"),
    ("kv_adopted", "serve_kv_adopted",
     "requests admitted on adopted prefill KV instead of a local "
     "prefill (disaggregated serving, decode phase)"),
)


# serving histograms additionally exported as ONE labeled gauge family
# per name — dt_serve_ttft_ms{q="0.5|0.95|0.99"} — so a Prometheus/
# Grafana latency panel selects quantiles by label instead of stitching
# the flattened _p50/_p95/_p99 names (only heartbeat p95s were visible
# that way before); the flattened spellings keep rendering for
# compatibility with existing dashboards
_QUANTILE_HISTS = ("serve.ttft_ms", "serve.tpot_ms")
_QUANTILE_LABELS = ((50.0, "0.5"), (95.0, "0.95"), (99.0, "0.99"))


def render(registry=None, fleet=None) -> str:
    """The exposition body — separable from the server for tests and for
    one-shot dumps."""
    reg = registry if registry is not None else obs.registry()
    lines: list[str] = []
    snap = reg.snapshot()
    for name in sorted(snap):
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_prom_value(snap[name])}")
    peek = getattr(reg, "peek", None)
    for name in (_QUANTILE_HISTS if peek is not None else ()):
        hist = peek(name)
        if hist is None or not hasattr(hist, "percentiles") \
                or not hist.count:
            continue
        ps = hist.percentiles(tuple(q for q, _ in _QUANTILE_LABELS))
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        for q, label in _QUANTILE_LABELS:
            lines.append(
                f'{pn}{{q="{label}"}} {_prom_value(ps[f"p{int(q)}"])}')
    try:
        # device observatory (utils/devprof.py): dt_prog_*{prog,bucket}
        # per-program cost/exec/roofline series + the labeled
        # dt_compile_ms{prog,bucket} compile histogram riding next to
        # the unlabeled dt_compile_ms_* registry aggregate. Cardinality
        # is bounded by devprof's own max_programs cap (the PR-11
        # Registry(max_names=) discipline); empty when disabled.
        lines.extend(devprof.prom_lines())
    except Exception:  # a broken observatory must not 500 the registry
        logger.exception("obs_http: devprof render failed")
    try:
        # SLO burn rates (engine/health.BurnRateMonitor, fed by the
        # request-trace stream): dt_slo_burn{slo,window} — cardinality
        # is rules x the fixed window-label set. Function-level import:
        # utils must not import engine at module load.
        from ..engine import health as _health
        burn = _health.live_burn_monitor()
        if burn is not None:
            lines.append("# HELP dt_slo_burn error-budget burn rate "
                         "(bad_fraction/budget) per SLO per window")
            lines.append("# TYPE dt_slo_burn gauge")
            for (slo, window), v in sorted(burn.gauges().items()):
                lines.append(
                    f'dt_slo_burn{{slo="{_label_escape(slo)}",'
                    f'window="{_label_escape(window)}"}} '
                    f"{_prom_value(v)}")
    except Exception:  # a broken monitor must not 500 the registry
        logger.exception("obs_http: burn render failed")
    if fleet is not None:
        try:
            ledger = fleet.ledger()
        except Exception:  # a broken monitor must not 500 the registry
            logger.exception("obs_http: fleet ledger render failed")
            ledger = {}
        for field, pn_suffix, help_txt in _FLEET_SERIES:
            rows = [(rec, rec.get(field)) for rec in ledger.values()
                    if isinstance(rec.get(field), (int, float))]
            if not rows:
                continue
            pn = "dt_" + pn_suffix
            lines.append(f"# HELP {pn} {help_txt}")
            lines.append(f"# TYPE {pn} gauge")
            for rec, v in rows:
                labels = (f'role="{_label_escape(rec["role"])}",'
                          f'hotkey="{_label_escape(rec["hotkey"])}"')
                lines.append(f"{pn}{{{labels}}} {_prom_value(v)}")
        breaches = [rec for rec in ledger.values() if rec.get("breaches")]
        if breaches:
            lines.append("# TYPE dt_fleet_slo_breached gauge")
            for rec in breaches:
                for rule in rec["breaches"]:
                    lines.append(
                        f'dt_fleet_slo_breached{{role='
                        f'"{_label_escape(rec["role"])}",hotkey='
                        f'"{_label_escape(rec["hotkey"])}",rule='
                        f'"{_label_escape(rule)}"}} 1.0')
    return "\n".join(lines) + "\n"


def render_stacks() -> str:
    """All-thread stack dump (the /debug/stacks body): thread name +
    daemon flag + current frames, newest frame last — what "where is the
    serve loop stuck" needs, without attaching a debugger."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        t = by_ident.get(ident)
        name = t.name if t is not None else f"ident-{ident}"
        daemon = " daemon" if t is not None and t.daemon else ""
        out.append(f"--- thread {name}{daemon} (ident {ident}) ---")
        out.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(out) + "\n"


class ObsHTTPExporter:
    """Serve :func:`render` on ``http://host:port/metrics``.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    returned by :meth:`start` and kept in ``.port``. The server thread
    and every handler thread are daemons; :meth:`close` shuts the
    listener down and joins the serve thread (idempotent)."""

    def __init__(self, port: int = 0, *, host: str = "127.0.0.1",
                 registry=None, fleet=None, role: str | None = None,
                 profile_dir: str | None = None):
        self.host = host
        self.port = port
        self.registry = registry
        self.fleet = fleet
        self.role = role
        # where /debug/profile writes its traces; None lazily falls back
        # to a tempdir so the endpoint works on an unconfigured exporter
        self.profile_dir = profile_dir
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> int:
        if self._server is not None:
            return self.port
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: no per-scrape spam
                logger.debug("obs_http: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj) -> None:
                self._send(code, (json.dumps(obj, default=float)
                                  + "\n").encode(), "application/json")

            def _debug(self, path: str, query: dict) -> None:
                from . import flight
                if path == "/debug/stacks":
                    self._send(200, render_stacks().encode(),
                               "text/plain; charset=utf-8")
                elif path == "/debug/dump":
                    rec = flight.recorder()
                    if rec is None:
                        self._send_json(503, {
                            "error": "no flight recorder configured "
                                     "(--flight-events 0?)"})
                        return
                    bundle = rec.freeze("debug_dump")
                    if query.get("publish", ["0"])[0] not in ("0", ""):
                        rec.publish(bundle)
                    self._send_json(200, bundle)
                elif path == "/debug/profile":
                    try:
                        ms = float(query.get("ms", ["500"])[0])
                    except ValueError:
                        self._send_json(400, {"error": "ms must be a "
                                                       "number"})
                        return
                    pdir = exporter.profile_dir
                    if pdir is None:
                        import tempfile
                        pdir = exporter.profile_dir = tempfile.mkdtemp(
                            prefix="dt-debug-profile-")
                    try:
                        info = flight.capture_profile(pdir, ms)
                    except RuntimeError as e:
                        self._send_json(409, {"error": str(e)})
                        return
                    except Exception:
                        logger.exception("obs_http: profile capture "
                                         "failed")
                        self._send_json(500, {"error": "profile capture "
                                                       "failed"})
                        return
                    self._send_json(200, info)
                else:
                    self._send_json(404, {"error": "unknown debug "
                                                   "endpoint"})

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path, _, rawq = self.path.partition("?")
                if path.startswith("/debug/"):
                    self._debug(path, parse_qs(rawq))
                    return
                if path in ("/metrics", "/"):
                    try:
                        body = render(exporter.registry,
                                      exporter.fleet).encode()
                    except Exception:
                        logger.exception("obs_http: render failed")
                        self._send(500, b"render failed\n", "text/plain")
                        return
                    self._send(200, body,
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    reg = (exporter.registry if exporter.registry
                           is not None else obs.registry())
                    info = {"ok": True, "role": exporter.role,
                            "metrics": len(reg),
                            "fleet_nodes": (len(exporter.fleet.nodes)
                                            if exporter.fleet is not None
                                            else None)}
                    self._send(200, (json.dumps(info) + "\n").encode(),
                               "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"obs-http-{self.port}",
                                        daemon=True)
        self._thread.start()
        _LIVE_EXPORTERS.add(self)
        logger.info("obs exporter serving on http://%s:%d/metrics",
                    self.host, self.port)
        return self.port

    @property
    def running(self) -> bool:
        return self._server is not None

    def close(self) -> None:
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        _LIVE_EXPORTERS.discard(self)
