"""Cross-cutting utilities: timeouts, metrics, logging."""

from .timeout import ChainTimeout, run_with_timeout
from .metrics import MetricsSink, InMemorySink, JSONLSink, multi_sink

__all__ = ["ChainTimeout", "run_with_timeout",
           "MetricsSink", "InMemorySink", "JSONLSink", "multi_sink"]
