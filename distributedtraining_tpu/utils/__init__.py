"""Cross-cutting utilities: timeouts, metrics, identity, lifecycle, load."""

from .timeout import ChainTimeout, run_with_timeout
from .metrics import MetricsSink, InMemorySink, JSONLSink, multi_sink
from .auto_update import AutoUpdater, file_version, git_remote_version

__all__ = ["ChainTimeout", "run_with_timeout",
           "MetricsSink", "InMemorySink", "JSONLSink", "multi_sink",
           "Identity", "generate_wallets", "load_wallets",
           "AutoUpdater", "file_version", "git_remote_version"]

_IDENTITY_NAMES = {"Identity", "generate_wallets", "load_wallets"}


def __getattr__(name):
    # identity.py needs the third-party `cryptography` package; importing it
    # lazily keeps the role entry points runnable on boxes without it
    if name in _IDENTITY_NAMES:
        from . import identity
        return getattr(identity, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
