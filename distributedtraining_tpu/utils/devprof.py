"""Device performance observatory: XLA cost attribution, per-program
execution histograms, and a hardware roofline model.

The fleet is thoroughly observed (spans, heartbeats, flight bundles,
fleetsim scorecards) but the *device* was a black box: ``compile.ms``
and wall-clock said how long things took, never where a step's FLOPs
and bytes actually went. This module is the one home of per-program
device accounting — every hot path registers its cached jitted
programs here:

- :func:`wrap` wraps a jitted callable under a **closed program
  vocabulary** (:data:`PROGRAMS`, the same producer-side lint
  discipline as ``flight.EVENT_KINDS``: an unknown name raises at the
  producer, so a new hot path cannot ship unobserved under an ad-hoc
  name). Per (program, bucket) the observatory records:

  * lowered ``cost_analysis()`` FLOPs / bytes-accessed, probed once on
    the first dispatch (skip-not-fail: backends without a cost model
    leave the fields None, everything else keeps working);
  * compile time — first-dispatch wall, the same convention as the
    shared ``compile.ms`` histogram (trace + compile + one dispatch);
  * an execution-time histogram. On CPU the wrapper BLOCKS on the
    result (``jax.block_until_ready``) so the histogram is real device
    time; on TPU it never blocks — the dispatch runs under a
    ``jax.profiler.TraceAnnotation("dt.<prog>[<bucket>]")`` so an
    on-demand device trace (``flight.capture_profile``, the
    ``/debug/profile`` endpoint) attributes device time to the same
    names this registry reports, and the histogram records host
    dispatch time (still the pipeline-stall truth the host sees).

- :func:`track` is the host-phase sibling for hot paths that are NOT
  device programs (the packed-wire densify): same records, no cost
  probe, ``host: true`` in exports.

- a **roofline model** (:data:`ROOFLINES`): a small per-chip peak
  bf16 FLOP/s + HBM bandwidth table keyed on
  ``jax.devices()[0].device_kind`` with an explicit unknown fallback,
  yielding achieved-fraction and arithmetic-intensity gauges per
  program — the quantity TPU systems papers reason with across
  hardware generations, and the yardstick the Pallas-kernel PR will
  be judged against.

Everything is off until :func:`enable` runs (the utils/obs.py
contract): a wrapped program costs ONE module-flag branch when
disabled, and bench._time_devprof_overhead pins the enabled cost at
< 2% of step time. Exposure: ``obs.flush`` mirrors :func:`snapshot`
into the role's JSONL sink as a ``{"devprof": ...}`` record
(scripts/perf_report.py joins those into the where-the-time-goes
table), utils/obs_http.py renders :func:`prom_lines`
(``dt_prog_*{prog,bucket}`` + ``dt_compile_ms{prog,bucket}``), and
:func:`anatomy` derives the step-time anatomy fields heartbeats and
fleet_report carry (host-blocked vs device vs data-wait).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

from . import obs

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Closed program vocabulary (the flight.EVENT_KINDS discipline)
# ---------------------------------------------------------------------------

# name -> description. wrap()/track() REJECT names outside this table, so
# every observed device program is registered here first — the tier-1
# lint test (tests/test_devprof.py) additionally asserts every jax.jit
# site in the five hot-path modules is wrapped or explicitly exempted.
PROGRAMS: dict[str, str] = {
    "train.step": "miner fwd+bwd+optimizer train step (engine/train.py)",
    "train.eval": "token-weighted eval step (engine/train.py)",
    "push.snapshot": "delta snapshot / wire-v2 pack program "
                     "(engine/train.py)",
    "eval.cohort": "bucketed K-candidate cohort eval "
                   "(engine/batched_eval.py)",
    "eval.stack": "cohort stack+pad assembly (engine/batched_eval.py)",
    "eval.pad": "stacked-cohort pad-up (engine/batched_eval.py)",
    "merge.sharded": "cached shard_map cohort merge "
                     "(parallel/collectives.py)",
    "delta.finite": "fused tree-finiteness guard (delta.py)",
    "delta.merge": "stacked weighted merge (delta.py)",
    "delta.screen": "fused dense cohort screen (delta.py)",
    "delta.screen_packed": "fused packed-wire cohort screen (delta.py)",
    "delta.accumulate": "scatter-add delta accumulation (delta.py)",
    "delta.dequant_scatter": "fused dequant->scatter-add packed "
                             "accumulate via the Pallas kernel "
                             "(delta.py / ops/dequant_scatter.py)",
    "delta.densify": "host densify of packed wire entries (delta.py)",
    "serve.prefill": "per-T-bucket prefill program (engine/serve.py)",
    "serve.decode": "per-(slot,page)-bucket decode step "
                    "(engine/serve.py)",
    "serve.decode_attn": "standalone fused paged-attention decode "
                         "program (ops/paged_attention.py; the in-step "
                         "copy is attributed under serve.decode)",
    "serve.decode_sample": "sampled (temperature/top-p, seeded PRNG) "
                           "twin of serve.decode — same forward, "
                           "scatter, and (slot,page) buckets "
                           "(engine/serve.py)",
    "serve.prefill_ctx": "suffix prefill over shared prefix-cache "
                         "pages, per (T,page)-bucket (engine/serve.py)",
    "serve.sample_tok": "single-row seeded sampler for the first "
                        "token after prefill (engine/serve.py)",
    "serve.page_copy": "whole-page KV copy — the copy-on-write "
                       "primitive behind prefix sharing "
                       "(engine/serve.py)",
    "serve.kv_adopt": "adopted-KV page write on a decode worker — "
                      "scatter one fetched [L,P,Hkv,D] page pair into "
                      "the pool (disaggregated serving; "
                      "engine/kv_transfer.py)",
    "serve.draft": "draft-model propose step / context prefill over "
                   "the drafter's own paged KV pool "
                   "(engine/speculative.py)",
    "serve.verify": "speculative K+1-position batched verify pass — "
                    "the multi-token twin of serve.decode on the same "
                    "(slot,page) buckets (engine/serve.py)",
}


# ---------------------------------------------------------------------------
# Roofline table
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Roofline:
    """Per-DEVICE peaks (what one ``jax.devices()`` entry can do):
    dense bf16 FLOP/s and HBM bytes/s, from public spec sheets.
    ``known=False`` is the explicit unknown-chip fallback — achieved
    fractions are then omitted, never fabricated."""
    device_kind: str
    peak_flops: float | None
    hbm_bytes_per_s: float | None
    known: bool = True

    @property
    def ridge_intensity(self) -> float | None:
        """FLOPs/byte at the compute/memory-bound ridge point."""
        if not self.peak_flops or not self.hbm_bytes_per_s:
            return None
        return self.peak_flops / self.hbm_bytes_per_s


# substring of the (lowercased) device_kind -> (peak bf16 FLOP/s,
# HBM bytes/s) PER JAX DEVICE. v2/v3 expose one device per CORE (half a
# chip); v4 onward are megacore (one device per chip). The e-generations
# report themselves as "v5 lite"/"v6 lite"; the ladder checks most
# specific first so "v5p" never matches a bare "v5" entry.
_ROOFLINE_LADDER: tuple[tuple[tuple[str, ...], float, float], ...] = (
    (("v6e", "v6 lite"), 918e12, 1640e9),
    (("v5p",), 459e12, 2765e9),
    (("v5e", "v5 lite"), 197e12, 819e9),
    (("v4",), 275e12, 1228e9),
    (("v3",), 61.5e12, 450e9),
    (("v2",), 22.5e12, 350e9),
)

# exported for docs/tests: device-kind spellings the ladder recognizes
ROOFLINES: dict[str, Roofline] = {
    keys[0]: Roofline(keys[0], fl, bw)
    for keys, fl, bw in _ROOFLINE_LADDER
}


def roofline_for(device_kind: str) -> Roofline:
    """Roofline for a device-kind string; unknown chips (and CPU hosts)
    get the explicit ``known=False`` fallback."""
    text = (device_kind or "").lower()
    for keys, fl, bw in _ROOFLINE_LADDER:
        if any(k in text for k in keys):
            return Roofline(device_kind, fl, bw)
    return Roofline(device_kind or "unknown", None, None, known=False)


def current_roofline() -> Roofline:
    """Roofline of this process's first device (cached per enable)."""
    st = _STATE
    if st.roofline is None:
        try:
            import jax
            st.roofline = roofline_for(jax.devices()[0].device_kind)
        except Exception:  # backend init failure degrades, never raises
            st.roofline = Roofline("unknown", None, None, known=False)
    return st.roofline


def cost_analysis_available() -> bool:
    """Probe whether this backend's lowered programs expose a cost model
    with flops/bytes (the CPU backend does; exotic plugins may not).
    Used by tests to skip-not-fail attribution assertions."""
    try:
        import jax
        import jax.numpy as jnp
        ca = jax.jit(lambda x: x * 2.0).lower(
            jnp.ones((4,), jnp.float32)).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        return isinstance(ca, dict) and "flops" in ca
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Per-program records
# ---------------------------------------------------------------------------

class ProgramStats:
    """One (program, bucket) cell of the registry."""

    __slots__ = ("prog", "bucket", "host", "calls", "compile_ms",
                 "flops", "bytes_accessed", "cost_probed", "exec_ms")

    def __init__(self, prog: str, bucket: str, *, host: bool = False):
        self.prog = prog
        self.bucket = bucket
        self.host = host
        self.calls = 0
        self.compile_ms: float | None = None
        self.flops: float | None = None
        self.bytes_accessed: float | None = None
        self.cost_probed = False
        self.exec_ms = obs.Histogram("devprof.exec_ms", capacity=512)

    # -- derived gauges ------------------------------------------------------
    def arithmetic_intensity(self) -> float | None:
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def achieved(self, roofline: Roofline) -> tuple[float | None,
                                                    float | None]:
        """(achieved peak-FLOPs fraction, achieved HBM-bandwidth
        fraction) at the exec p50 — None wherever the cost model or the
        roofline has no number (unknown chip, host phase)."""
        if self.host or not self.exec_ms.count:
            return None, None
        p50_s = self.exec_ms.percentiles((50.0,))["p50"] / 1e3
        if not p50_s or p50_s <= 0:
            return None, None
        ff = bf = None
        if self.flops and roofline.peak_flops:
            ff = (self.flops / p50_s) / roofline.peak_flops
        if self.bytes_accessed and roofline.hbm_bytes_per_s:
            bf = (self.bytes_accessed / p50_s) / roofline.hbm_bytes_per_s
        return ff, bf

    def as_record(self, roofline: Roofline) -> dict:
        ff, bf = self.achieved(roofline)
        rec: dict[str, Any] = {
            "prog": self.prog, "bucket": self.bucket, "calls": self.calls,
            "host": self.host, "compile_ms": self.compile_ms,
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "exec_ms": self.exec_ms.snapshot(),
        }
        ai = self.arithmetic_intensity()
        if ai is not None:
            rec["arith_intensity"] = round(ai, 4)
        if ff is not None:
            rec["achieved_flops_frac"] = round(ff, 6)
        if bf is not None:
            rec["achieved_bw_frac"] = round(bf, 6)
        return rec


class _DevprofState:
    def __init__(self, *, max_programs: int = 64):
        self.lock = threading.Lock()
        self.records: dict[tuple[str, str], ProgramStats] = {}
        self.max_programs = max_programs
        self.dropped = 0
        self.roofline: Roofline | None = None
        # resolved lazily on the first observed call (enable() must not
        # force backend init inside a role that probes the backend with
        # its own timeout discipline, bench._require_backend)
        self.block: bool | None = None
        self.annotate: bool | None = None
        self.probe_costs = True


_STATE = _DevprofState()
_ON = False


def enable(*, block: bool | None = None, annotate: bool | None = None,
           max_programs: int = 64, probe_costs: bool = True) -> None:
    """Turn the observatory on. ``block``/``annotate`` override the
    per-backend defaults (block on non-TPU so exec histograms are real
    device time; annotate on TPU so device traces carry program names);
    ``max_programs`` caps (program, bucket) cardinality — past it, new
    cells are dropped-and-counted, the obs ``Registry(max_names=)``
    discipline."""
    global _ON
    st = _STATE
    with st.lock:
        st.max_programs = max(1, int(max_programs))
        st.block = block
        st.annotate = annotate
        st.probe_costs = probe_costs
    _ON = True
    # flush mirroring rides the obs sink: every obs.flush() then logs a
    # {"devprof": ...} record next to the registry snapshot
    obs.attach_devprof(on_flush)


def disable() -> None:
    global _ON
    _ON = False
    obs.attach_devprof(None)


def enabled() -> bool:
    return _ON


def reset() -> None:
    """Drop ALL observatory state (records, roofline cache, the enabled
    flag) — the obs.reset()/flight.reset() teardown contract; the
    tests/conftest.py hygiene guard asserts every test module leaves
    this clean."""
    global _STATE, _ON
    _STATE = _DevprofState()
    _ON = False
    obs.attach_devprof(None)


def dirty() -> bool:
    return _ON or bool(_STATE.records) or _STATE.dropped > 0


def _resolve_backend() -> None:
    st = _STATE
    if st.block is not None and st.annotate is not None:
        return
    platform = "cpu"
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        pass
    if st.block is None:
        st.block = platform != "tpu"
    if st.annotate is None:
        st.annotate = platform == "tpu"


def _get_record(prog: str, bucket: str, *,
                host: bool = False) -> ProgramStats | None:
    st = _STATE
    key = (prog, bucket)
    with st.lock:
        rec = st.records.get(key)
        if rec is None:
            if len(st.records) >= st.max_programs:
                st.dropped += 1
                return None
            rec = st.records[key] = ProgramStats(prog, bucket, host=host)
        return rec


def _bucket_of(bucket, args, kwargs) -> str:
    if bucket is None:
        return "-"
    if callable(bucket):
        try:
            bucket = bucket(args, kwargs)
        except Exception:
            return "-"
    return str(bucket)


def _probe_cost(rec: ProgramStats, fn, args, kwargs) -> None:
    """One-time FLOPs/bytes probe: lower the jitted callable against the
    first call's (still-live — this runs BEFORE the dispatch that may
    donate them) arguments and read the XLA cost analysis. Lowering is
    abstract (shapes only) and happens once per (program, bucket);
    backends without a cost model just leave the fields None."""
    rec.cost_probed = True
    lower = getattr(fn, "lower", None)
    if lower is None:
        return
    try:
        ca = lower(*args, **kwargs).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            fl = ca.get("flops")
            by = ca.get("bytes accessed")
            if isinstance(fl, (int, float)) and fl >= 0:
                rec.flops = float(fl)
            if isinstance(by, (int, float)) and by >= 0:
                rec.bytes_accessed = float(by)
    except Exception:
        logger.debug("devprof: cost probe failed for %s[%s]",
                     rec.prog, rec.bucket, exc_info=True)


def _observed_call(prog: str, bucket, fn, args, kwargs):
    _resolve_backend()
    rec = _get_record(prog, _bucket_of(bucket, args, kwargs))
    first = rec is not None and rec.calls == 0
    import jax
    # the one-time cost probe (an abstract trace+lower) runs INSIDE the
    # timed window: its wall time lands in compile_ms with the rest of
    # the first dispatch, so attributed time accounts for everything
    # the observatory itself adds to the step
    t0 = time.perf_counter()
    if first and _STATE.probe_costs:
        _probe_cost(rec, fn, args, kwargs)
    if _STATE.annotate:
        with jax.profiler.TraceAnnotation(
                f"dt.{prog}[{rec.bucket if rec else '-'}]"):
            out = fn(*args, **kwargs)
    else:
        out = fn(*args, **kwargs)
    if _STATE.block:
        try:
            jax.block_until_ready(out)
        except Exception:
            pass  # non-array outputs: dispatch time is the record
    dur_ms = (time.perf_counter() - t0) * 1e3
    if rec is not None:
        with _STATE.lock:
            rec.calls += 1
            if first:
                # first-dispatch wall = trace + compile (+ dispatch/exec),
                # the _timed_compile convention — and it stays OUT of the
                # exec histogram so percentiles describe the steady state
                rec.compile_ms = round(dur_ms, 3)
            else:
                rec.exec_ms.observe(dur_ms)
    return out


def wrap(name: str, fn: Callable, *, bucket=None) -> Callable:
    """Register a jitted program under ``name`` (closed vocabulary —
    unknown names raise, the producer-side lint). ``bucket`` labels the
    program's compiled-variant family: a static value, or a callable
    ``(args, kwargs) -> value`` evaluated per call (bucket ladders where
    one wrapped callable serves many compiled shapes). Returns a wrapper
    that is a single-branch pass-through until :func:`enable`."""
    if name not in PROGRAMS:
        raise ValueError(
            f"unknown devprof program {name!r}; register it in "
            f"devprof.PROGRAMS (closed vocabulary: {sorted(PROGRAMS)})")

    def wrapped(*args, **kwargs):
        if not _ON:
            return fn(*args, **kwargs)
        return _observed_call(name, bucket, fn, args, kwargs)

    wrapped.__wrapped__ = fn
    wrapped._devprof_name = name  # type: ignore[attr-defined]
    lower = getattr(fn, "lower", None)
    if lower is not None:
        # AOT/introspection users (scripts/scale_aot.py, HLO-pinning
        # tests) keep the jitted callable's lower() through the wrapper
        wrapped.lower = lower  # type: ignore[attr-defined]
    return wrapped


@contextmanager
def track(name: str, *, bucket=None):
    """Host-phase sibling of :func:`wrap` for hot paths that are not
    device programs (the packed-wire densify): records wall time into
    the same per-(program, bucket) histograms, no cost probe."""
    if name not in PROGRAMS:
        raise ValueError(
            f"unknown devprof program {name!r}; register it in "
            f"devprof.PROGRAMS (closed vocabulary: {sorted(PROGRAMS)})")
    if not _ON:
        yield
        return
    rec = _get_record(name, _bucket_of(bucket, (), {}), host=True)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if rec is not None:
            with _STATE.lock:
                rec.calls += 1
                rec.exec_ms.observe((time.perf_counter() - t0) * 1e3)


# ---------------------------------------------------------------------------
# Exposure
# ---------------------------------------------------------------------------

def records() -> list[ProgramStats]:
    with _STATE.lock:
        return list(_STATE.records.values())


def snapshot() -> dict:
    """JSON-able registry dump: per-program records + the roofline +
    cardinality accounting — the ``{"devprof": ...}`` record obs.flush
    mirrors into the role's JSONL sink and perf_report joins."""
    rl = current_roofline()
    recs = records()
    return {
        "roofline": {"device_kind": rl.device_kind,
                     "peak_flops": rl.peak_flops,
                     "hbm_bytes_per_s": rl.hbm_bytes_per_s,
                     "known": rl.known},
        "programs": sorted((r.as_record(rl) for r in recs),
                           key=lambda r: (r["prog"], r["bucket"])),
        "dropped_programs": _STATE.dropped,
    }


def on_flush(sink, role: str | None = None) -> None:
    """obs.flush hook: mirror the registry snapshot through the role's
    sink (one record per flush; perf_report keeps the last per role)."""
    if not _ON or sink is None or not _STATE.records:
        return
    sink.log({"devprof": snapshot(), "role": role or "unknown"})


# step histogram -> (device programs attributed to it, data-wait
# histogram): the step-time anatomy join. Sums are averages over the
# step count so the parts are additive (host-blocked = step - device).
_ANATOMY = (
    ("miner.step_ms", ("train.step",), "miner.data_wait_ms"),
    ("serve.step_ms", ("serve.decode", "serve.prefill"), None),
)


def anatomy() -> dict[str, float]:
    """Step-time anatomy fields (``anat.*``, heartbeat-lintable names):
    average step wall-clock, the device-program share attributed by this
    registry, the host-blocked remainder, and data wait. Empty when the
    observatory is off or no step histogram has samples."""
    if not _ON:
        return {}
    reg = obs.registry()
    for step_name, progs, wait_name in _ANATOMY:
        h = reg.peek(step_name)
        if h is None or not getattr(h, "count", 0):
            continue
        steps = h.count
        step_avg = h.total / steps
        device_total = sum(
            r.exec_ms.total + (r.compile_ms or 0.0)
            for r in records() if r.prog in progs and not r.host)
        device_avg = device_total / steps
        out = {
            "anat.step_ms": round(step_avg, 3),
            "anat.device_ms": round(device_avg, 3),
            "anat.host_ms": round(max(0.0, step_avg - device_avg), 3),
            "anat.device_frac": round(
                min(1.0, device_avg / step_avg) if step_avg > 0 else 0.0,
                4),
        }
        if wait_name is not None:
            w = reg.peek(wait_name)
            if w is not None and getattr(w, "count", 0):
                out["anat.data_wait_ms"] = round(w.total / w.count, 3)
        return out
    return {}


def _esc(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
                 .replace("\n", r"\n")


def prom_lines() -> list[str]:
    """Prometheus exposition lines for utils/obs_http.py:
    ``dt_prog_*{prog,bucket}`` labeled series per registered program
    (calls, flops, bytes, exec-time quantiles, achieved fractions,
    arithmetic intensity) plus ``dt_compile_ms{prog,bucket}`` — the
    labeled per-program compile series riding next to the unlabeled
    ``compile.ms`` registry aggregate. Empty when disabled."""
    if not _ON:
        return []
    rl = current_roofline()
    recs = records()
    if not recs:
        return []
    lines: list[str] = []
    series: dict[str, list[str]] = {}

    def emit(pn: str, labels: str, v) -> None:
        series.setdefault(pn, []).append(f"{pn}{{{labels}}} {float(v)!r}")

    for r in sorted(recs, key=lambda r: (r.prog, r.bucket)):
        lab = f'prog="{_esc(r.prog)}",bucket="{_esc(r.bucket)}"'
        emit("dt_prog_calls", lab, r.calls)
        if r.compile_ms is not None:
            emit("dt_compile_ms", lab, r.compile_ms)
        if r.flops is not None:
            emit("dt_prog_flops", lab, r.flops)
        if r.bytes_accessed is not None:
            emit("dt_prog_bytes_accessed", lab, r.bytes_accessed)
        if r.exec_ms.count:
            ps = r.exec_ms.percentiles((50.0, 95.0, 99.0))
            for q, qv in (("0.5", ps["p50"]), ("0.95", ps["p95"]),
                          ("0.99", ps["p99"])):
                emit("dt_prog_exec_ms", lab + f',q="{q}"', qv)
        ai = r.arithmetic_intensity()
        if ai is not None:
            emit("dt_prog_arith_intensity", lab, ai)
        ff, bf = r.achieved(rl)
        if ff is not None:
            emit("dt_prog_achieved_flops_frac", lab, ff)
        if bf is not None:
            emit("dt_prog_achieved_bw_frac", lab, bf)
    for pn in sorted(series):
        lines.append(f"# TYPE {pn} gauge")
        lines.extend(series[pn])
    if _STATE.dropped:
        lines.append("# TYPE dt_prog_dropped gauge")
        lines.append(f"dt_prog_dropped {float(_STATE.dropped)!r}")
    return lines


def achieved_fractions() -> dict[str, float]:
    """prog -> best achieved-FLOPs fraction across buckets — the compact
    per-program utilization summary bench records carry so ``--baseline``
    can gate utilization regressions, not just headline tokens/sec."""
    rl = current_roofline()
    out: dict[str, float] = {}
    for r in records():
        ff, _ = r.achieved(rl)
        if ff is not None:
            out[r.prog] = max(out.get(r.prog, 0.0), round(ff, 6))
    return out
