"""Auto-update: version polling + self-restart.

Reference parity: `hivetrain/utils/auto_update.py:6-60` polls the version
constant on GitHub (`template/__init__.py:24-27`), and the pm2 watchdogs in
`run_miner.sh:229-268` re-clone and restart the process when the published
version moves. Here the same lifecycle is a small, injectable component:

- ``version_source`` is any zero-arg callable returning the *published*
  version string (git-remote polling and file polling ship below; an HTTP
  source is a one-liner for deployments that have one).
- on mismatch, ``update_cmd`` runs (e.g. ``git pull --ff-only``) and the
  process re-execs itself in place (``os.execv``), which under pm2-style
  supervision (scripts/run_*.sh) is a clean restart into the new code.

Nothing here touches JAX state: re-exec happens between engine steps, and a
failed poll/update never interrupts training.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from typing import Callable, Optional, Sequence

logger = logging.getLogger(__name__)


def git_remote_version(repo_dir: str, *, ref: str = "origin/main",
                       version_file: str = "distributedtraining_tpu/__init__.py"
                       ) -> Optional[str]:
    """Published version = __version__ in ``version_file`` at ``ref`` after a
    fetch. Returns None when the remote is unreachable (air-gapped boxes keep
    running on their local version)."""
    try:
        subprocess.run(["git", "fetch", "--quiet"], cwd=repo_dir, check=True,
                       timeout=60, capture_output=True)
        blob = subprocess.run(
            ["git", "show", f"{ref}:{version_file}"], cwd=repo_dir,
            check=True, timeout=10, capture_output=True, text=True).stdout
    except (subprocess.SubprocessError, OSError):
        return None
    return parse_version(blob)


def file_version(path: str) -> Optional[str]:
    """Published version from a shared file (operator drops a new version
    string to trigger a fleet restart)."""
    try:
        with open(path) as f:
            blob = f.read()
    except OSError:
        return None
    return parse_version(blob) or blob.strip() or None


def parse_version(blob: str) -> Optional[str]:
    for line in blob.splitlines():
        line = line.strip()
        if line.startswith("__version__"):
            return line.split("=", 1)[1].strip().strip("\"'")
    blob = blob.strip()
    # a bare "x.y.z" file is also accepted
    if blob and all(p.isdigit() for p in blob.split(".")) and "." in blob:
        return blob
    return None


class AutoUpdater:
    """Poll ``version_source``; when it differs from ``current_version``, run
    ``update_cmd`` and re-exec. Designed to be driven by a PeriodicAction in
    the role loops or by the supervision scripts' restart cycle."""

    def __init__(self, current_version: str,
                 version_source: Callable[[], Optional[str]], *,
                 update_cmd: Sequence[str] | None = ("git", "pull",
                                                     "--ff-only"),
                 repo_dir: str = ".",
                 restart: Callable[[], None] | None = None,
                 hard_recovery_ref: Optional[str] = "origin/main"):
        """``hard_recovery_ref``: when the polite ``update_cmd`` fails (a
        dirty or diverged tree — an operator's local edit, a crashed
        half-merge), fall back to ``git fetch && git reset --hard <ref>``
        so a fleet member never stays wedged on old code. The reference
        achieves the same end by re-cloning the whole repo on version
        mismatch (run_miner.sh:229-268); a hard reset converges to the
        identical tree without re-downloading history. None disables the
        fallback (deployments where local state must never be discarded)."""
        self.current_version = current_version
        self.version_source = version_source
        self.update_cmd = list(update_cmd) if update_cmd else None
        self.repo_dir = repo_dir
        self.restart = restart if restart is not None else self._reexec
        self.hard_recovery_ref = hard_recovery_ref
        self._clean_failures = 0  # consecutive clean-tree update failures

    def _run(self, cmd: Sequence[str]) -> bool:
        try:
            subprocess.run(list(cmd), cwd=self.repo_dir, check=True,
                           timeout=300, capture_output=True)
            return True
        except (subprocess.SubprocessError, OSError):
            return False

    def _dirty_or_diverged(self) -> Optional[bool]:
        """True when the tree has local edits or history that is not an
        ancestor of the recovery ref — the two states the destructive
        fallback exists for. None when git itself can't answer (never
        destroy state on an unknown)."""
        try:
            status = subprocess.run(
                ["git", "status", "--porcelain"], cwd=self.repo_dir,
                check=True, timeout=60, capture_output=True,
                text=True).stdout.strip()
            if status:
                return True
            ancestor = subprocess.run(
                ["git", "merge-base", "--is-ancestor", "HEAD",
                 self.hard_recovery_ref], cwd=self.repo_dir,
                timeout=60, capture_output=True)
            return ancestor.returncode != 0
        except (subprocess.SubprocessError, OSError):
            return None

    def _update(self) -> bool:
        if self._run(self.update_cmd):
            self._clean_failures = 0
            return True
        if self.hard_recovery_ref is None:
            logger.error("auto-update: update command failed and hard "
                         "recovery is disabled; not restarting")
            return False
        # Distinguish a transient failure (unreachable remote mid-pull)
        # from the states hard recovery is for: a fetch that fails now is
        # transient — retry next poll rather than discard operator edits.
        if not self._run(("git", "fetch", "--quiet")):
            logger.warning("auto-update: %s failed and fetch is failing "
                           "too (transient network?); will retry next "
                           "poll, not hard-recovering",
                           " ".join(self.update_cmd))
            return False
        culprit = self._dirty_or_diverged()
        if culprit is None:
            logger.warning("auto-update: %s failed and the tree state is "
                           "undeterminable; not hard-recovering",
                           " ".join(self.update_cmd))
            return False
        if not culprit:
            # Clean + not diverged usually means the failure was transient
            # — but some clean states (detached HEAD at an old commit, a
            # branch with no upstream) fail the polite command on EVERY
            # poll. One failure with a working fetch gets a retry; a
            # second consecutive one is persistent and recovers hard.
            self._clean_failures += 1
            if self._clean_failures < 2:
                logger.warning(
                    "auto-update: %s failed but the tree is clean and not "
                    "diverged (transient failure?); retrying next poll",
                    " ".join(self.update_cmd))
                return False
            logger.warning(
                "auto-update: %s failed %d consecutive polls with a "
                "reachable remote and a clean tree (detached HEAD / no "
                "upstream?); hard-recovering to %s",
                " ".join(self.update_cmd), self._clean_failures,
                self.hard_recovery_ref)
        else:
            logger.warning("auto-update: %s failed on a dirty/diverged "
                           "tree; hard-recovering to %s",
                           " ".join(self.update_cmd), self.hard_recovery_ref)
        ok = self._run(("git", "reset", "--hard", self.hard_recovery_ref))
        if ok:
            self._clean_failures = 0
        else:
            logger.error("auto-update: hard recovery failed; not restarting")
        return ok

    def check(self) -> bool:
        """One poll. Returns True when an update was triggered (the default
        restart does not return)."""
        try:
            published = self.version_source()
        except Exception:
            logger.exception("auto-update: version poll failed")
            return False
        if published is None or published == self.current_version:
            return False
        logger.info("auto-update: %s -> %s", self.current_version, published)
        if self.update_cmd and not self._update():
            return False
        self.restart()
        return True

    @staticmethod
    def _reexec() -> None:  # pragma: no cover — replaces the process image
        os.execv(sys.executable, [sys.executable] + sys.argv)
