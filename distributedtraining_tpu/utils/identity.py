"""Participant identities: keypair generation, signing, verification.

The reference leans on Bittensor wallets for identity — mass-generation in
`hivetrain/utils/generate_wallets.py:9-41`, hotkey-signed metric posts in
`hivetrain/utils/dummy_miner.py:63-68` (`keypair.sign(message)` verified by
the receiving validator). This module provides the same capability without
the bittensor SDK: Ed25519 keypairs (via the `cryptography` package), a
hotkey string derived from the public key, JSON-file wallet storage, and
detached sign/verify over arbitrary payload bytes.

When the bittensor chain backend is active, its ss58 wallets take over;
these identities serve the local/HF deployments and the load-generation
tooling (utils/loadgen.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Optional

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey, Ed25519PublicKey)


def _hotkey_from_public(pub_bytes: bytes) -> str:
    """Short, stable, human-greppable id: 'hk' + 20 hex chars of SHA-256."""
    return "hk" + hashlib.sha256(pub_bytes).hexdigest()[:20]


@dataclasses.dataclass
class Identity:
    hotkey: str
    public_bytes: bytes
    _private: Optional[Ed25519PrivateKey] = None

    # -- creation -----------------------------------------------------------
    @classmethod
    def generate(cls) -> "Identity":
        priv = Ed25519PrivateKey.generate()
        pub = priv.public_key().public_bytes_raw()
        return cls(hotkey=_hotkey_from_public(pub), public_bytes=pub,
                   _private=priv)

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Identity":
        priv = Ed25519PrivateKey.from_private_bytes(data)
        pub = priv.public_key().public_bytes_raw()
        return cls(hotkey=_hotkey_from_public(pub), public_bytes=pub,
                   _private=priv)

    @classmethod
    def public_only(cls, pub_bytes: bytes) -> "Identity":
        return cls(hotkey=_hotkey_from_public(pub_bytes),
                   public_bytes=pub_bytes)

    # -- signing ------------------------------------------------------------
    def sign(self, message: bytes) -> bytes:
        if self._private is None:
            raise ValueError("public-only identity cannot sign")
        return self._private.sign(message)

    def verify(self, message: bytes, signature: bytes) -> bool:
        try:
            Ed25519PublicKey.from_public_bytes(self.public_bytes).verify(
                signature, message)
            return True
        except InvalidSignature:
            return False

    # -- storage ------------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        payload = {
            "hotkey": self.hotkey,
            "public": self.public_bytes.hex(),
            "private": self._private.private_bytes_raw().hex()
            if self._private else None,
        }
        tmp = path + ".tmp"
        # owner-only from birth: the payload holds the private key, so the
        # tmp file must never exist with umask-default permissions. POSIX
        # applies the mode only at creation, so a stale tmp left by a crash
        # would keep its old permissions — unlink it and create exclusively.
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "Identity":
        with open(path) as f:
            payload = json.load(f)
        if payload.get("private"):
            ident = cls.from_private_bytes(bytes.fromhex(payload["private"]))
        else:
            ident = cls.public_only(bytes.fromhex(payload["public"]))
        if ident.hotkey != payload["hotkey"]:
            raise ValueError(f"wallet {path}: hotkey does not match key")
        return ident


def generate_wallets(directory: str, n: int) -> list[Identity]:
    """Mass-generate n wallets under ``directory`` (generate_wallets.py:9-41
    parity: the reference loops bt.wallet(...).create)."""
    idents = []
    for i in range(n):
        ident = Identity.generate()
        ident.save(os.path.join(directory, f"wallet_{i}.json"))
        idents.append(ident)
    return idents


def load_wallets(directory: str) -> list[Identity]:
    names = sorted(f for f in os.listdir(directory) if f.endswith(".json"))
    return [Identity.load(os.path.join(directory, f)) for f in names]
