"""Peer registry: a liveness-checked rendezvous service.

Reference parity for the bootstrap-server pool
(`hivetrain/utils/bootstrap_server.py:39-115`): the reference keeps 10 DHT
addresses behind a Flask app, health-checks them, and hands one to each
joining peer. The DHT era is dead (hivemind remnants), but the capability —
"a new node finds live peers without the chain" — is still useful for local
and HF-transport clusters, so this is the same service rebuilt on the
stdlib: a threaded HTTP server with TTL-pruned registrations.

Endpoints (JSON):
  POST /register   {"hotkey": ..., "address": ...} -> {"ok": true}
  GET  /peers      -> {"peers": [{"hotkey", "address", "age_s"}, ...]}
  GET  /health     -> {"ok": true, "peers": N}

Client helpers wrap urllib so roles need no HTTP dependency.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

DEFAULT_TTL = 120.0  # seconds a registration stays live without refresh
DEFAULT_MAX_PEERS = 4096   # bound on distinct hotkeys a client can grow
MAX_FIELD_LEN = 512        # bound on hotkey/address string lengths


class PeerRegistry:
    """In-process registry state (also usable directly in tests)."""

    def __init__(self, ttl: float = DEFAULT_TTL,
                 max_peers: int = DEFAULT_MAX_PEERS,
                 rate_limit_seconds: float = 0.0,
                 now_fn=None):
        from ..chain.base import RateLimiter
        self.ttl = ttl
        self.max_peers = max_peers
        self._peers: dict[str, tuple[str, float]] = {}
        self._lock = threading.Lock()
        # refuse-on-hammering like the chain surface (btt_connector.py:
        # 454-480) — but NO permanent blacklist: the hotkey here is an
        # unauthenticated self-claim, so banning it would let an attacker
        # spoof a victim's id into a permanent lockout
        self.limiter = RateLimiter(rate_limit_seconds, now_fn=now_fn,
                                   blacklist_after=None)

    def register(self, hotkey: str, address: str,
                 now: Optional[float] = None) -> bool:
        """True = accepted; False = refused by the rate limiter."""
        if not self.limiter.allow(hotkey):
            return False
        t = time.time() if now is None else now
        with self._lock:
            # bounded memory: a hostile client POSTing unlimited distinct
            # hotkeys must not grow the server without limit (the reference
            # bootstrap pool this replaces was a fixed-size list)
            if hotkey not in self._peers and len(self._peers) >= self.max_peers:
                self._peers = {h: (a, ts) for h, (a, ts) in self._peers.items()
                               if t - ts <= self.ttl}
                while len(self._peers) >= self.max_peers:
                    oldest = min(self._peers, key=lambda h: self._peers[h][1])
                    del self._peers[oldest]
            self._peers[hotkey] = (address, t)
        return True

    def peers(self, now: Optional[float] = None) -> list[dict]:
        t = time.time() if now is None else now
        with self._lock:
            # prune-on-read keeps the server stateless between requests
            self._peers = {h: (a, ts) for h, (a, ts) in self._peers.items()
                           if t - ts <= self.ttl}
            return [{"hotkey": h, "address": a, "age_s": round(t - ts, 1)}
                    for h, (a, ts) in sorted(self._peers.items())]


class _Handler(BaseHTTPRequestHandler):
    registry: PeerRegistry  # set by serve()

    def _send(self, code: int, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/peers":
            self._send(200, {"peers": self.registry.peers()})
        elif self.path == "/health":
            self._send(200, {"ok": True, "peers": len(self.registry.peers())})
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/register":
            self._send(404, {"error": "not found"})
            return
        try:
            # clamp below 0 too: a hostile Content-Length of -1 would make
            # read() block until the client hangs up, pinning the thread
            n = max(0, min(int(self.headers.get("Content-Length", 0)),
                           1 << 16))
            body = json.loads(self.rfile.read(n) or b"{}")
            hotkey, address = str(body["hotkey"]), str(body["address"])
            if len(hotkey) > MAX_FIELD_LEN or len(address) > MAX_FIELD_LEN:
                raise ValueError("field too long")
        except (ValueError, KeyError, TypeError):  # non-dict JSON included
            self._send(400, {"error": "bad request"})
            return
        if not self.registry.register(hotkey, address):
            self._send(429, {"error": "rate limited"})
            return
        self._send(200, {"ok": True})

    def log_message(self, *args):  # quiet by default
        pass


def serve(host: str = "127.0.0.1", port: int = 0,
          ttl: float = DEFAULT_TTL,
          rate_limit_seconds: float = 0.0) -> tuple[ThreadingHTTPServer, str]:
    """Start the registry server on a daemon thread; returns (server, url).
    port=0 picks a free port."""
    registry = PeerRegistry(ttl=ttl, rate_limit_seconds=rate_limit_seconds)
    handler = type("Handler", (_Handler,), {"registry": registry})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.registry = registry  # type: ignore[attr-defined]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://{host}:{srv.server_address[1]}"


# -- client helpers ----------------------------------------------------------

def register_peer(url: str, hotkey: str, address: str,
                  timeout: float = 5.0) -> bool:
    req = urllib.request.Request(
        url.rstrip("/") + "/register",
        data=json.dumps({"hotkey": hotkey, "address": address}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.load(resp).get("ok", False)
    except OSError:
        return False


def get_peers(url: str, timeout: float = 5.0) -> list[dict]:
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/peers",
                                    timeout=timeout) as resp:
            return json.load(resp).get("peers", [])
    except OSError:
        return []
