"""Flight recorder & postmortem plane: crash forensics captured BEFORE
the failure, retrievable AFTER it.

The fleet detects (engine/health.py SLO rules), remediates
(engine/remediate.py), and serves (engine/serve.py) — but every
diagnosis so far is live-only: when a miner is chaos-killed, a lease
flips, or a swap stalls, the registry state, recent spans, and
heartbeat history on that node die with its process, and
scripts/fleet_report.py can only show the survivors' view. At fleet
scale node death is the steady state, not the exception
(PAPERS.md 2606.15870), so forensics must be recorded continuously and
frozen the moment something goes wrong:

- every role keeps a bounded in-memory **ring** of structured events
  (:class:`FlightRecorder`): span closes (hooked into utils/obs.span),
  registry snapshots whenever the metric VOCABULARY grows, SLO
  arm/fire, lease transitions, serving hot-swap outcomes, publish
  outcomes (including torn wire-v2 shard sets), last heartbeats sent
  and observed, and the role's sanitized boot config. Recording is one
  lock-guarded deque append — ``bench._time_flight_overhead`` pins the
  cost on the miner step loop under 2%.
- on an SLO breach, a remediation action, a lease flip, or a crash
  (``sys.excepthook`` / ``threading.excepthook`` / ``atexit``), the
  ring **freezes** into a content-addressed postmortem bundle — a JSON
  document whose ``bundle_id`` is the hash of its contents — published
  through the role's existing Transport under the reserved
  ``__pm__.<role>.<hotkey>`` id (transport/base.py). Bundles therefore
  travel exactly like deltas: chaos-gated (transport/chaos.py), signed
  when the fleet signs (SignedTransport.publish_delta_raw envelopes
  them), coordinator-gated on pods, and fetchable from a DEAD remote
  node's storage slot by any peer.
- the bundle also logs through the role's metrics sink as a
  ``{"postmortem": ...}`` record, so rotated JSONL streams retain every
  bundle even though the transport slot holds only the newest one.
  ``scripts/postmortem.py`` joins bundles from N roles with the obs
  JSONL segments into one causal round timeline keyed on
  cid/round/revision.

Schema discipline mirrors the heartbeat plane: the producer rejects
unknown event kinds at ``record()`` time (:data:`EVENT_KINDS` is the
closed vocabulary), and :func:`parse_bundle` re-validates everything on
the consumer side — a hostile bundle can at worst misdescribe its own
node. Everything is a no-op until :func:`configure` runs (the same
off-by-default contract as utils/obs.py), and the tests/conftest.py
hygiene guard fails any module that leaves a recorder, crash hook, or
``/debug/profile`` session behind.
"""

from __future__ import annotations

import atexit
import dataclasses
import hashlib
import json
import logging
import os
import re
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any

from . import obs

logger = logging.getLogger(__name__)

PM_VERSION = 1

# hard cap on one serialized bundle (publish side truncates the OLDEST
# events to fit; fetch side refuses anything bigger — the same
# size-before-parse posture as transport/base.parse_delta_meta)
PM_MAX_BYTES = 1 << 20

# the closed event vocabulary: kind -> description
# (docs/observability.md renders this table; scripts/postmortem.py
# mirrors the keys — update both when extending). record() rejects
# anything else at the PRODUCER, parse_bundle drops it at the consumer.
EVENT_KINDS: dict[str, str] = {
    "config": "sanitized role configuration at recorder boot",
    "span": "one obs.span close (name, dur_ms, cid, error flag)",
    "metrics": "registry snapshot, taken when the metric vocabulary "
               "(registry digest) changed",
    "anomaly": "AnomalyMonitor trigger (reason + armed capture)",
    "slo": "SLO rule fired against a fleet node (engine/health.py)",
    "lease": "publication-lease transition: acquired / lost / "
             "renew_failed / takeover (engine/remediate.py)",
    "swap": "serving-plane base hot-swap outcome (engine/serve.py)",
    "publish": "delta/base publish outcome: ok / failed / torn "
               "(engine/publish.py)",
    "heartbeat": "heartbeat sent (own) or fresh beat observed (fleet)",
    "remediation": "quarantine / probation / readmission action",
    "crash": "unhandled exception or process-exit capture",
    "lineage.record": "a merge's provenance record frozen/published "
                      "(engine/lineage.py)",
    "lineage.drift": "merged-model quality drift detected by the "
                     "EWMA/CUSUM detector (engine/lineage.py)",
    "serve.trace.exemplar": "one tail-exemplar request frozen by the "
                            "reqtrace reservoir: request_id, status, "
                            "ttft/tpot, stage count (utils/reqtrace.py)",
    "serve.trace.stage": "one stage of a frozen exemplar's timeline: "
                         "request_id, stage, rel_ms/dur_ms, batched "
                         "step count + stage fields (utils/reqtrace.py)",
    "note": "free-form operator/debug annotation",
}

_MAX_STR = 400
_MAX_EVENT_FIELDS = 24
_MAX_BUNDLE_EVENTS = 4096
_MAX_TB_LINES = 40

# config keys matching this pattern have their VALUES redacted in the
# sanitized-config event (never ship wallet/key material in a bundle
# that travels the public artifact plane)
_SECRET_RE = re.compile(r"wallet|token|secret|password|credential|privkey",
                        re.IGNORECASE)


def check_event_kind(kind: str) -> str:
    """Producer-side schema lint (the flight twin of
    obs.check_metric_name): an event kind outside the closed vocabulary
    must fail at the call site, not parse-time at every consumer."""
    if kind not in EVENT_KINDS:
        raise ValueError(f"unknown flight event kind {kind!r}; expected "
                         f"one of {sorted(EVENT_KINDS)}")
    return kind


def sanitize_config(cfg) -> dict:
    """Flatten a RunConfig (or plain dict) into a bundle-safe dict:
    scalars only, strings capped, secret-ish keys redacted by NAME
    (value presence still reads — "a wallet path was set" is forensic
    signal; its value is not)."""
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        items = dataclasses.asdict(cfg)
    elif isinstance(cfg, dict):
        items = cfg
    else:
        return {}
    out: dict[str, Any] = {}
    for k, v in items.items():
        if v is None:
            continue
        if _SECRET_RE.search(str(k)):
            out[str(k)[:_MAX_STR]] = "<redacted>"
        elif isinstance(v, bool):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = float(v)
        elif isinstance(v, str):
            out[k] = v[:_MAX_STR]
        else:  # nested structures (MeshSpec) flatten to their repr
            out[k] = str(v)[:_MAX_STR]
    return out


def _clean_fields(fields: dict) -> dict:
    """Bound one event's payload: linted-ish keys, capped strings,
    numbers/bools verbatim, one flat numeric dict allowed (the registry
    snapshot a ``metrics`` event carries)."""
    out: dict[str, Any] = {}
    for k, v in list(fields.items())[:_MAX_EVENT_FIELDS]:
        k = str(k)[:64]
        if v is None:
            continue
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        elif isinstance(v, str):
            out[k] = v[:_MAX_STR]
        elif isinstance(v, dict):
            out[k] = {str(dk)[:120]: float(dv)
                      for dk, dv in list(v.items())[:256]
                      if isinstance(dv, (int, float))}
        else:
            out[k] = str(v)[:_MAX_STR]
    return out


class FlightRecorder:
    """Bounded ring of structured events for ONE (role, hotkey).

    Thread contract: ``record`` is called from the train loop, the
    publish worker, the heartbeat timer, the serve-watch thread, and
    HTTP handler threads concurrently — everything mutating the ring
    holds ``_lock``. ``freeze`` snapshots under the lock and builds the
    bundle outside it."""

    def __init__(self, role: str, hotkey: str, *, capacity: int = 512,
                 transport=None, config=None, clock=time.time):
        if capacity < 8:
            raise ValueError(f"capacity must be >= 8, got {capacity}")
        self.role = role
        self.hotkey = hotkey
        self.capacity = capacity
        self.transport = transport
        self.clock = clock
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.recorded = 0           # lifetime events (ring keeps the tail)
        self.seq = 0                # bundles frozen by this recorder
        self.published = 0
        self.publish_failures = 0
        self.last_bundle: dict | None = None
        self._names_seen = 0        # registry vocab size at last check
        self._config = sanitize_config(config) if config is not None else None
        if self._config:
            self.record("config", keys=float(len(self._config)))

    # -- recording -----------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        check_event_kind(kind)
        ev = {"t": round(float(self.clock()), 6), "kind": kind,
              **_clean_fields(fields)}
        with self._lock:
            self._ring.append(ev)
            self.recorded += 1

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    # -- obs hooks (utils/obs.py calls these when a recorder is attached) ----
    def on_span(self, name: str, dur_ms: float, cid: str | None,
                ok: bool) -> None:
        f: dict[str, Any] = {"name": name, "dur_ms": round(dur_ms, 3)}
        if cid is not None:
            f["cid"] = cid
        if not ok:
            f["error"] = True
        self.record("span", **f)
        self._maybe_snapshot_metrics()

    def on_flush(self, snap: dict) -> None:
        self._maybe_snapshot_metrics()

    def _maybe_snapshot_metrics(self) -> None:
        """Record a registry snapshot when the metric VOCABULARY changed
        (len is O(1); the digest itself is only computed on change) —
        the ring then always holds the registry state at each
        instrumentation transition, not a per-step flood."""
        reg = obs.registry()
        n = len(reg)
        if n == self._names_seen:
            return
        self._names_seen = n
        self.record("metrics", digest=reg.digest(), names=float(n),
                    snapshot=reg.snapshot())

    # -- freezing ------------------------------------------------------------
    def freeze(self, reason: str, *, exc=None) -> dict:
        """Freeze the ring into a content-addressed postmortem bundle.
        ``exc`` is an (exc_type, exc, tb) triple for crash captures."""
        self.seq += 1
        bundle: dict[str, Any] = {
            "pm": PM_VERSION, "role": self.role, "hotkey": self.hotkey,
            "t": float(self.clock()), "seq": self.seq,
            "reason": str(reason)[:_MAX_STR],
            "recorded": self.recorded, "capacity": self.capacity,
            "events": self.events(),
            "registry": {k: float(v)
                         for k, v in obs.registry().snapshot().items()},
            "registry_digest": obs.registry_digest(),
        }
        if self._config is not None:
            bundle["config"] = dict(self._config)
        if exc is not None:
            et, ev, tb = exc
            bundle["crash"] = {
                "type": getattr(et, "__name__", str(et))[:_MAX_STR],
                "message": str(ev)[:_MAX_STR],
                "traceback": "".join(
                    traceback.format_exception(et, ev, tb)
                )[-_MAX_TB_LINES * 120:],
            }
        bundle["bundle_id"] = bundle_digest(bundle)
        self.last_bundle = bundle
        obs.count("flight.bundles")
        return bundle

    def publish(self, bundle: dict) -> bool:
        """Ship one bundle through the Transport (reserved ``__pm__``
        id) and the metrics sink. Never raises — forensics must degrade,
        not take the role down with them. Oversized rings truncate their
        OLDEST events to fit :data:`PM_MAX_BYTES` (newest evidence
        wins)."""
        sink = obs.current_sink()
        if sink is not None:
            try:
                sink.log({"postmortem": bundle})
            except Exception:
                logger.exception("flight: bundle sink emit failed")
        if self.transport is None:
            return False
        from ..transport import base as tbase
        data = json.dumps(bundle, default=float).encode()
        while len(data) > PM_MAX_BYTES and bundle["events"]:
            drop = max(1, len(bundle["events"]) // 4)
            bundle = dict(bundle, events=bundle["events"][drop:],
                          truncated=True)
            bundle["bundle_id"] = bundle_digest(bundle)
            data = json.dumps(bundle, default=float).encode()
        try:
            tbase.publish_postmortem(self.transport, self.role,
                                     self.hotkey, data)
            self.published += 1
            obs.count("flight.bundles_published")
            logger.info("flight: published postmortem %s (%s, %d events)",
                        bundle["bundle_id"], bundle["reason"],
                        len(bundle["events"]))
            return True
        except Exception:
            self.publish_failures += 1
            obs.count("flight.publish_failures")
            logger.warning("flight: postmortem publish failed (%s); the "
                           "bundle survives in the metrics sink",
                           bundle["reason"], exc_info=True)
            return False


def bundle_digest(bundle: dict) -> str:
    """Content address of a bundle: sha256 over the canonical JSON of
    everything but the id itself."""
    body = {k: v for k, v in bundle.items() if k != "bundle_id"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, default=float).encode()
    ).hexdigest()[:16]


def parse_bundle(data) -> dict | None:
    """Defensive consumer read of a PEER-CONTROLLED bundle (bytes or an
    already-decoded dict): size-capped, versioned, role/hotkey/reason
    validated, and every event re-screened against :data:`EVENT_KINDS`
    — unknown kinds are REJECTED (dropped and counted in the returned
    bundle's ``events_rejected``), mirroring the heartbeat schema lint.
    Returns a normalized dict or None; never raises."""
    if isinstance(data, (bytes, bytearray)):
        if len(data) > PM_MAX_BYTES:
            return None
        try:
            data = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return None
    if not isinstance(data, dict):
        return None
    v = data.get("pm")
    if not isinstance(v, (int, float)) or int(v) < 1:
        return None
    role, hotkey = data.get("role"), data.get("hotkey")
    if not (isinstance(role, str) and 0 < len(role) <= 200):
        return None
    if not (isinstance(hotkey, str) and 0 < len(hotkey) <= 200):
        return None
    out: dict[str, Any] = {
        "pm": int(v), "role": role, "hotkey": hotkey,
        "t": float(data["t"]) if isinstance(data.get("t"),
                                            (int, float)) else 0.0,
        "reason": str(data.get("reason", ""))[:_MAX_STR],
    }
    bid = data.get("bundle_id")
    if isinstance(bid, str) and 0 < len(bid) <= 64:
        out["bundle_id"] = bid
    events, rejected = [], 0
    raw = data.get("events")
    if isinstance(raw, list):
        for ev in raw[:_MAX_BUNDLE_EVENTS]:
            if not (isinstance(ev, dict) and ev.get("kind") in EVENT_KINDS
                    and isinstance(ev.get("t"), (int, float))):
                rejected += 1
                continue
            events.append({"t": float(ev["t"]), "kind": ev["kind"],
                           **_clean_fields({k: v for k, v in ev.items()
                                            if k not in ("t", "kind")})})
    out["events"] = events
    out["events_rejected"] = rejected
    for key in ("registry", "config", "crash"):
        if isinstance(data.get(key), dict):
            out[key] = data[key]
    return out


def fetch_bundle(transport, role: str, hotkey: str) -> dict | None:
    """Fetch + validate ``role``/``hotkey``'s current postmortem bundle
    from the Transport — how a SURVIVOR reads a dead peer's forensics.
    Envelope-tolerant without verification, like every other unsigned
    artifact read."""
    from .. import signing
    from ..transport import base as tbase
    try:
        data = tbase.fetch_postmortem_bytes(transport, role, hotkey)
    except Exception:
        obs.count("flight.fetch_errors")
        logger.warning("flight: bundle fetch failed for %s/%s", role,
                       hotkey, exc_info=True)
        return None
    if data is None:
        return None
    return parse_bundle(signing.strip_envelope(data))


# ---------------------------------------------------------------------------
# Process-wide state (the obs pattern: off until configured)
# ---------------------------------------------------------------------------

class _FlightState:
    def __init__(self):
        self.recorder: FlightRecorder | None = None
        self.hooks_installed = False
        self.prev_excepthook = None
        self.prev_threading_hook = None


_STATE = _FlightState()


def configure(role: str, hotkey: str, *, transport=None,
              capacity: int = 512, config=None,
              clock=time.time) -> FlightRecorder:
    """Bind the process's flight recorder (one per role process, like
    obs.configure). Re-configuring replaces the recorder."""
    rec = FlightRecorder(role, hotkey, capacity=capacity,
                         transport=transport, config=config, clock=clock)
    _STATE.recorder = rec
    obs.attach_flight(rec)
    return rec


def recorder() -> FlightRecorder | None:
    return _STATE.recorder


def enabled() -> bool:
    return _STATE.recorder is not None


def dirty() -> bool:
    """What the conftest hygiene guard checks after each test module."""
    return _STATE.recorder is not None


def hooks_installed() -> bool:
    return _STATE.hooks_installed


def record(kind: str, **fields) -> None:
    """Record one event — single-branch no-op when no recorder is
    configured, so instrumentation sites may call unconditionally. The
    kind lint still applies when enabled (a typo'd kind must fail in the
    first test that exercises the site)."""
    rec = _STATE.recorder
    if rec is None:
        return
    rec.record(kind, **fields)


def freeze_and_publish(reason: str, *, exc=None) -> str | None:
    """Freeze the ring and ship the bundle; returns the content-address
    ``bundle_id`` (the reference remediation attaches to the ledger) or
    None when no recorder is configured. Never raises."""
    rec = _STATE.recorder
    if rec is None:
        return None
    try:
        bundle = rec.freeze(reason, exc=exc)
        rec.publish(bundle)
        return bundle["bundle_id"]
    except Exception:
        logger.exception("flight: freeze/publish failed (%s)", reason)
        return None


def reset() -> None:
    """Drop the recorder and uninstall crash hooks — role exit and the
    conftest guard both route through here (mirrors obs.reset)."""
    uninstall_crash_hooks()
    _STATE.recorder = None
    obs.attach_flight(None)


def shutdown() -> None:
    """Role-main ``finally`` hook: if the role is exiting on an
    unhandled exception (KeyboardInterrupt and SystemExit are normal
    shutdowns, not crashes), freeze a crash bundle FIRST — the finally
    block runs before sys.excepthook would, and reset() would otherwise
    detach the recorder with the evidence still in memory."""
    et, ev, tb = sys.exc_info()
    if (et is not None and _STATE.recorder is not None
            and not issubclass(et, (KeyboardInterrupt, SystemExit,
                                    GeneratorExit))):
        record("crash", where="shutdown",
               type=getattr(et, "__name__", str(et)), message=str(ev))
        freeze_and_publish("crash", exc=(et, ev, tb))
    reset()


# ---------------------------------------------------------------------------
# Crash hooks
# ---------------------------------------------------------------------------

def _atexit_freeze() -> None:
    # last-breath bundle on interpreter exit: whatever the ring holds is
    # the final state the process can ever report
    if _STATE.recorder is not None:
        freeze_and_publish("exit")


def install_crash_hooks() -> None:
    """Install the unhandled-exception + atexit freeze triggers
    (idempotent). Role entry points call this after build; library/test
    code must not — the conftest guard fails modules that leak them."""
    if _STATE.hooks_installed:
        return
    _STATE.hooks_installed = True
    _STATE.prev_excepthook = sys.excepthook

    def _hook(et, ev, tb):
        try:
            if _STATE.recorder is not None:
                record("crash", where="main",
                       type=getattr(et, "__name__", str(et)),
                       message=str(ev))
                freeze_and_publish("crash", exc=(et, ev, tb))
        finally:
            (_STATE.prev_excepthook or sys.__excepthook__)(et, ev, tb)

    sys.excepthook = _hook
    _STATE.prev_threading_hook = threading.excepthook

    def _thook(args):
        try:
            if (_STATE.recorder is not None
                    and not issubclass(args.exc_type, SystemExit)):
                record("crash", where="thread",
                       thread=getattr(args.thread, "name", "?"),
                       type=getattr(args.exc_type, "__name__",
                                    str(args.exc_type)),
                       message=str(args.exc_value))
                freeze_and_publish(
                    "thread_crash",
                    exc=(args.exc_type, args.exc_value, args.exc_traceback))
        finally:
            prev = _STATE.prev_threading_hook or threading.__excepthook__
            prev(args)

    threading.excepthook = _thook
    atexit.register(_atexit_freeze)


def uninstall_crash_hooks() -> None:
    if not _STATE.hooks_installed:
        return
    _STATE.hooks_installed = False
    if _STATE.prev_excepthook is not None:
        sys.excepthook = _STATE.prev_excepthook
        _STATE.prev_excepthook = None
    if _STATE.prev_threading_hook is not None:
        threading.excepthook = _STATE.prev_threading_hook
        _STATE.prev_threading_hook = None
    try:
        atexit.unregister(_atexit_freeze)
    except Exception:  # pragma: no cover — unregister never raises today
        pass


# ---------------------------------------------------------------------------
# On-demand profiler capture (the /debug/profile endpoint)
# ---------------------------------------------------------------------------

MAX_PROFILE_MS = 10_000

# sessions whose jax profiler is running — the conftest hygiene guard
# force-stops and fails any module that leaves one live (same rule as
# utils/metrics._LIVE_CAPTURES; the two share one process-wide profiler)
_LIVE_PROFILES: set = set()
_PROFILE_LOCK = threading.Lock()


class ProfileSession:
    """One time-bounded ``jax.profiler`` window (vs the step-driven
    TraceCapture): started/stopped by :func:`capture_profile`, tracked
    so a wedged debug request cannot silently poison every later
    capture in the process."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.active = False

    def stop(self) -> None:
        if not self.active:
            return
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self.active = False
            _LIVE_PROFILES.discard(self)

    def __repr__(self):
        return f"ProfileSession({self.log_dir!r}, active={self.active})"


def live_profile_sessions() -> list[ProfileSession]:
    return list(_LIVE_PROFILES)


def capture_profile(log_dir: str, ms: float, *,
                    sleep=time.sleep) -> dict:
    """Capture ``ms`` milliseconds of ``jax.profiler`` trace into
    ``log_dir`` (TensorBoard/xprof-readable), synchronously on the
    calling thread. Exactly one session per process (the profiler is a
    global); a concurrent request raises RuntimeError (the endpoint
    answers 409)."""
    ms = max(1.0, min(float(ms), float(MAX_PROFILE_MS)))
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    sess = ProfileSession(log_dir)
    try:
        import jax
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
        sess.active = True
        _LIVE_PROFILES.add(sess)
        sleep(ms / 1e3)
    finally:
        try:
            sess.stop()
        finally:
            _PROFILE_LOCK.release()
    obs.count("flight.profiles_captured")
    record("note", what="debug_profile", trace_dir=log_dir, ms=ms)
    return {"trace_dir": log_dir, "ms": ms}
