"""Deadline wrapper for hang-prone external calls.

The reference forks a subprocess per chain RPC with a 60 s TTL
(run_in_subprocess, chain_manager.py:22-54) because substrate connections
wedge. Forking breaks under JAX (the child inherits TPU handles), so the
same hygiene is a daemon worker thread + deadline: the caller gets
ChainTimeout and moves on; an abandoned thread parks on dead IO and never
touches device state.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, TypeVar

T = TypeVar("T")


class ChainTimeout(TimeoutError):
    pass


def run_with_timeout(fn: Callable[[], T], timeout: float, *,
                     name: str = "op") -> T:
    q: queue.Queue = queue.Queue(maxsize=1)

    def worker():
        try:
            q.put(("ok", fn()))
        except BaseException as e:  # propagate any failure to the caller
            q.put(("err", e))

    t = threading.Thread(target=worker, daemon=True, name=f"timeout-{name}")
    t.start()
    try:
        kind, val = q.get(timeout=timeout)
    except queue.Empty:
        raise ChainTimeout(f"{name} exceeded {timeout}s") from None
    if kind == "err":
        raise val
    return val
