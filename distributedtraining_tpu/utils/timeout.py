"""Deadline wrapper for hang-prone external calls.

The reference forks a subprocess per chain RPC with a 60 s TTL
(run_in_subprocess, chain_manager.py:22-54) because substrate connections
wedge. Forking breaks under JAX (the child inherits TPU handles), so the
same hygiene is a daemon worker thread + deadline: the caller gets
ChainTimeout and moves on; an abandoned thread parks on dead IO and never
touches device state.

Unlike the reference's fork, a parked Python thread cannot be killed — so
abandonment is *accounted for* instead of ignored:

- every timeout registers the worker in a live-abandoned set;
  ``abandoned_workers()`` (live, self-pruning) and ``abandoned_total()``
  (monotonic) ride ``utils.metrics.device_metrics()`` into every role's
  metric stream, so a long-lived validator on a flaky substrate shows
  the leak instead of silently accumulating it;
- callers can pass ``on_timeout`` to kill the IO object the worker is
  parked on (closing a dead websocket unblocks the blocked recv, the
  worker raises and exits, and the "leak" resolves itself — the
  reference gets the same effect by killing the forked child);
- past ``ABANDON_WARN_THRESHOLD`` live abandoned workers a warning logs
  on every further timeout, naming the remedy.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Above this many LIVE parked workers the wrapper complains loudly: the
# caller is timing out repeatedly without an on_timeout that unblocks the
# dead connection, and each hang costs a thread + socket until then.
ABANDON_WARN_THRESHOLD = 8

_abandoned_lock = threading.Lock()
_abandoned: list[threading.Thread] = []
_abandoned_total = 0


class ChainTimeout(TimeoutError):
    pass


def abandoned_workers() -> int:
    """Live worker threads abandoned by a past timeout (self-pruning:
    workers whose IO eventually returned — or was killed via
    ``on_timeout`` — drop out). Exported as a gauge by the role loops."""
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        return len(_abandoned)


def abandoned_total() -> int:
    """Monotonic count of timeouts that abandoned a worker (a counter
    metric; live leakage is ``abandoned_workers()``)."""
    return _abandoned_total


def run_with_timeout(fn: Callable[[], T], timeout: float, *,
                     name: str = "op",
                     on_timeout: Optional[Callable[[], None]] = None) -> T:
    """Run ``fn`` on a daemon thread; raise ChainTimeout after ``timeout``
    seconds. ``on_timeout`` (optional) runs on the CALLER's thread right
    after the deadline fires — close/kill the connection object ``fn`` is
    blocked on there so the abandoned worker can actually exit."""
    global _abandoned_total
    q: queue.Queue = queue.Queue(maxsize=1)

    def worker():
        try:
            q.put(("ok", fn()))
        except BaseException as e:  # propagate any failure to the caller
            q.put(("err", e))

    t = threading.Thread(target=worker, daemon=True, name=f"timeout-{name}")
    t.start()
    try:
        kind, val = q.get(timeout=timeout)
    except queue.Empty:
        with _abandoned_lock:
            _abandoned[:] = [x for x in _abandoned if x.is_alive()]
            _abandoned.append(t)
            _abandoned_total += 1
            live = len(_abandoned)
        if on_timeout is not None:
            try:
                on_timeout()
            except Exception:
                logger.exception("%s: on_timeout hook failed", name)
        if live > ABANDON_WARN_THRESHOLD:
            logger.warning(
                "%s timed out; %d abandoned worker threads are still "
                "parked (total timeouts: %d) — pass on_timeout to kill "
                "the wedged connection so they can exit", name, live,
                _abandoned_total)
        raise ChainTimeout(f"{name} exceeded {timeout}s") from None
    if kind == "err":
        raise val
    return val
