"""Load generation: fake miner traffic for stress-testing validators.

Reference parity: `hivetrain/utils/dummy_miner.py:25-82` fakes hotkey-signed
miner metric posts at validators, and `utils/bootstrap_stress.py:18-48`
hammers the bootstrap pool. Here the load generator speaks the framework's
real artifact plane: it mass-publishes plausible (or deliberately hostile)
weight deltas from many identities, so a validator/averager under test
exercises its full download -> screen -> score path at scale.

Poison modes map one-to-one onto the admission screens in delta.py /
serialization.py / signing.py: "nan" (has_nonfinite), "shape"
(shapes_match), "huge" (max_abs cap), "garbage" (msgpack structure
validation), "forged" (a well-formed delta in a signature envelope signed
by the WRONG key — the authenticity screen in transport/signed.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import jax
import numpy as np

from .identity import Identity

logger = logging.getLogger(__name__)

POISON_MODES = ("nan", "shape", "huge", "garbage", "forged")


def benign_delta(template: Any, rng: np.random.Generator,
                 scale: float = 1e-3):
    """A plausible random delta shaped like ``template``."""
    return jax.tree_util.tree_map(
        lambda x: (rng.standard_normal(np.shape(x)) * scale)
        .astype(np.float32), template)


def poisoned_delta(template: Any, mode: str, rng: np.random.Generator,
                   scale: float = 1e-3):
    """A hostile delta for ``mode`` in {"nan","shape","huge"} — each maps
    to exactly one admission screen (module docstring). The byte-level
    modes ("garbage","forged") need a transport and live on
    LoadGenerator. Public so protocol-scale scenarios (e.g.
    scripts/e2e_discriminate.py) can poison a SPECIFIC chain hotkey
    rather than a generated identity."""
    d = benign_delta(template, rng, scale)
    leaves, treedef = jax.tree_util.tree_flatten(d)
    if mode == "nan":
        leaves[0] = leaves[0].copy()
        leaves[0].flat[0] = np.nan
    elif mode == "shape":
        leaves[0] = np.zeros(np.asarray(leaves[0]).shape + (2,), np.float32)
    elif mode == "huge":
        leaves[0] = leaves[0] + np.float32(1e9)
    else:
        raise ValueError(f"unknown tree-level poison mode {mode!r}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


@dataclasses.dataclass
class LoadReport:
    published: int = 0
    poisoned: int = 0
    by_mode: dict = dataclasses.field(default_factory=dict)


class LoadGenerator:
    """Publishes synthetic deltas for ``n_miners`` identities."""

    def __init__(self, transport, template_params: Any, *,
                 n_miners: int = 10, scale: float = 1e-3,
                 poison_fraction: float = 0.0, seed: int = 0,
                 sign: bool = False):
        self.transport = transport
        self.template = template_params
        self.identities = [Identity.generate() for _ in range(n_miners)]
        self.scale = scale
        self.poison_fraction = poison_fraction
        self.rng = np.random.default_rng(seed)
        self.report = LoadReport()
        # sign=True: each identity signs its own artifacts (what honest
        # miners on a signed fleet do); numeric poisons then pass the
        # authenticity screen and must still be caught by the value screens.
        # "forged" is only meaningful on a signed fleet — unsigned readers
        # strip envelopes unverified, so a wrong-key artifact would read as
        # benign and the poison accounting would lie
        self.sign = sign
        self.poison_modes = POISON_MODES if sign else tuple(
            m for m in POISON_MODES if m != "forged")

    def _benign_delta(self):
        return benign_delta(self.template, self.rng, self.scale)

    def _poisoned_delta(self, mode: str):
        return poisoned_delta(self.template, mode, self.rng, self.scale)

    def publish_round(self) -> LoadReport:
        """One wave: every identity publishes once; a ``poison_fraction`` of
        them publish a hostile artifact instead."""
        n_poison = int(round(self.poison_fraction * len(self.identities)))
        for i, ident in enumerate(self.identities):
            if i < n_poison:
                mode = self.poison_modes[i % len(self.poison_modes)]
                self.report.poisoned += 1
                self.report.by_mode[mode] = self.report.by_mode.get(mode, 0) + 1
                if mode == "garbage":
                    self._publish_garbage(ident)
                    continue
                if mode == "forged":
                    self._publish_forged(ident)
                    continue
                delta = self._poisoned_delta(mode)
            else:
                delta = self._benign_delta()
            self._publish(ident, delta)
            self.report.published += 1
        return self.report

    def _publish(self, ident: Identity, tree) -> None:
        publish_raw = getattr(self.transport, "publish_raw", None)
        if self.sign and publish_raw is not None:
            from .. import serialization as ser
            from .. import signing
            env = signing.wrap(ser.to_msgpack(tree), ident,
                               signing.delta_context(ident.hotkey))
            publish_raw(ident.hotkey, env)
        else:
            self.transport.publish_delta(ident.hotkey, tree)

    def _publish_garbage(self, ident: Identity) -> None:
        """Raw malformed bytes, bypassing the serializer (a hostile miner is
        not obliged to run our code)."""
        raw = bytes(self.rng.integers(0, 256, 256, dtype=np.uint8))
        publish_raw = getattr(self.transport, "publish_raw", None)
        if publish_raw is not None:
            publish_raw(ident.hotkey, raw)
            self.report.published += 1
        else:  # transport without a raw path: wrong-structure tree instead
            self.transport.publish_delta(ident.hotkey,
                                         {"junk": np.zeros(7, np.float32)})
            self.report.published += 1

    def _publish_forged(self, ident: Identity) -> None:
        """A PLAUSIBLE delta signed by an attacker's key, published under the
        victim's hotkey — only the authenticity screen can catch this (the
        payload passes every numeric/shape screen)."""
        from .. import serialization as ser
        from .. import signing

        attacker = Identity.generate()
        payload = ser.to_msgpack(self._benign_delta())
        env = signing.wrap(payload, attacker,
                           signing.delta_context(ident.hotkey))
        publish_raw = getattr(self.transport, "publish_raw", None)
        if publish_raw is not None:
            publish_raw(ident.hotkey, env)
        else:  # no raw path: an unsigned publish is the closest forgery
            self.transport.publish_delta(ident.hotkey, self._benign_delta())
        self.report.published += 1

    def hotkeys(self) -> list[str]:
        return [i.hotkey for i in self.identities]

    def register_pubkeys(self, address_store) -> None:
        """Register every identity's pubkey (what honest miners do at boot;
        makes signatures mandatory for these hotkeys in SignedTransport)."""
        for ident in self.identities:
            address_store.store_pubkey(ident.hotkey, ident.public_bytes)
