"""Load generation: fake miner traffic for stress-testing validators.

Reference parity: `hivetrain/utils/dummy_miner.py:25-82` fakes hotkey-signed
miner metric posts at validators, and `utils/bootstrap_stress.py:18-48`
hammers the bootstrap pool. Here the load generator speaks the framework's
real artifact plane: it mass-publishes plausible (or deliberately hostile)
weight deltas from many identities, so a validator/averager under test
exercises its full download -> screen -> score path at scale.

Poison modes map one-to-one onto the admission screens in delta.py /
serialization.py / signing.py: "nan" (has_nonfinite), "shape"
(shapes_match), "huge" (max_abs cap), "garbage" (msgpack structure
validation), "forged" (a well-formed delta in a signature envelope signed
by the WRONG key — the authenticity screen in transport/signed.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import jax
import numpy as np

from . import reqtrace

try:  # identity needs the optional `cryptography` package; the poison
    # generators and the open-loop serving harness below do not — keep
    # them importable on minimal containers (engine/fleetsim.py relies
    # on this), and fail with the real reason only when identities are
    # actually requested
    from .identity import Identity
except ImportError:  # pragma: no cover - environment-dependent
    Identity = None

logger = logging.getLogger(__name__)


def _require_identity():
    if Identity is None:
        raise ImportError(
            "utils.identity needs the optional `cryptography` package; "
            "install the [identity] extra to generate signing identities")
    return Identity

POISON_MODES = ("nan", "shape", "huge", "garbage", "forged")


def benign_delta(template: Any, rng: np.random.Generator,
                 scale: float = 1e-3):
    """A plausible random delta shaped like ``template``."""
    return jax.tree_util.tree_map(
        lambda x: (rng.standard_normal(np.shape(x)) * scale)
        .astype(np.float32), template)


def poisoned_delta(template: Any, mode: str, rng: np.random.Generator,
                   scale: float = 1e-3):
    """A hostile delta for ``mode`` in {"nan","shape","huge"} — each maps
    to exactly one admission screen (module docstring). The byte-level
    modes ("garbage","forged") need a transport and live on
    LoadGenerator. Public so protocol-scale scenarios (e.g.
    scripts/e2e_discriminate.py) can poison a SPECIFIC chain hotkey
    rather than a generated identity."""
    d = benign_delta(template, rng, scale)
    leaves, treedef = jax.tree_util.tree_flatten(d)
    if mode == "nan":
        leaves[0] = leaves[0].copy()
        leaves[0].flat[0] = np.nan
    elif mode == "shape":
        leaves[0] = np.zeros(np.asarray(leaves[0]).shape + (2,), np.float32)
    elif mode == "huge":
        leaves[0] = leaves[0] + np.float32(1e9)
    else:
        raise ValueError(f"unknown tree-level poison mode {mode!r}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


@dataclasses.dataclass
class LoadReport:
    published: int = 0
    poisoned: int = 0
    by_mode: dict = dataclasses.field(default_factory=dict)


class LoadGenerator:
    """Publishes synthetic deltas for ``n_miners`` identities."""

    def __init__(self, transport, template_params: Any, *,
                 n_miners: int = 10, scale: float = 1e-3,
                 poison_fraction: float = 0.0, seed: int = 0,
                 sign: bool = False):
        self.transport = transport
        self.template = template_params
        ident = _require_identity()
        self.identities = [ident.generate() for _ in range(n_miners)]
        self.scale = scale
        self.poison_fraction = poison_fraction
        self.rng = np.random.default_rng(seed)
        self.report = LoadReport()
        # sign=True: each identity signs its own artifacts (what honest
        # miners on a signed fleet do); numeric poisons then pass the
        # authenticity screen and must still be caught by the value screens.
        # "forged" is only meaningful on a signed fleet — unsigned readers
        # strip envelopes unverified, so a wrong-key artifact would read as
        # benign and the poison accounting would lie
        self.sign = sign
        self.poison_modes = POISON_MODES if sign else tuple(
            m for m in POISON_MODES if m != "forged")

    def _benign_delta(self):
        return benign_delta(self.template, self.rng, self.scale)

    def _poisoned_delta(self, mode: str):
        return poisoned_delta(self.template, mode, self.rng, self.scale)

    def publish_round(self) -> LoadReport:
        """One wave: every identity publishes once; a ``poison_fraction`` of
        them publish a hostile artifact instead."""
        n_poison = int(round(self.poison_fraction * len(self.identities)))
        for i, ident in enumerate(self.identities):
            if i < n_poison:
                mode = self.poison_modes[i % len(self.poison_modes)]
                self.report.poisoned += 1
                self.report.by_mode[mode] = self.report.by_mode.get(mode, 0) + 1
                if mode == "garbage":
                    self._publish_garbage(ident)
                    continue
                if mode == "forged":
                    self._publish_forged(ident)
                    continue
                delta = self._poisoned_delta(mode)
            else:
                delta = self._benign_delta()
            self._publish(ident, delta)
            self.report.published += 1
        return self.report

    def _publish(self, ident: Identity, tree) -> None:
        publish_raw = getattr(self.transport, "publish_raw", None)
        if self.sign and publish_raw is not None:
            from .. import serialization as ser
            from .. import signing
            env = signing.wrap(ser.to_msgpack(tree), ident,
                               signing.delta_context(ident.hotkey))
            publish_raw(ident.hotkey, env)
        else:
            self.transport.publish_delta(ident.hotkey, tree)

    def _publish_garbage(self, ident: Identity) -> None:
        """Raw malformed bytes, bypassing the serializer (a hostile miner is
        not obliged to run our code)."""
        raw = bytes(self.rng.integers(0, 256, 256, dtype=np.uint8))
        publish_raw = getattr(self.transport, "publish_raw", None)
        if publish_raw is not None:
            publish_raw(ident.hotkey, raw)
            self.report.published += 1
        else:  # transport without a raw path: wrong-structure tree instead
            self.transport.publish_delta(ident.hotkey,
                                         {"junk": np.zeros(7, np.float32)})
            self.report.published += 1

    def _publish_forged(self, ident: Identity) -> None:
        """A PLAUSIBLE delta signed by an attacker's key, published under the
        victim's hotkey — only the authenticity screen can catch this (the
        payload passes every numeric/shape screen)."""
        from .. import serialization as ser
        from .. import signing

        attacker = Identity.generate()
        payload = ser.to_msgpack(self._benign_delta())
        env = signing.wrap(payload, attacker,
                           signing.delta_context(ident.hotkey))
        publish_raw = getattr(self.transport, "publish_raw", None)
        if publish_raw is not None:
            publish_raw(ident.hotkey, env)
        else:  # no raw path: an unsigned publish is the closest forgery
            self.transport.publish_delta(ident.hotkey, self._benign_delta())
        self.report.published += 1

    def hotkeys(self) -> list[str]:
        return [i.hotkey for i in self.identities]

    def register_pubkeys(self, address_store) -> None:
        """Register every identity's pubkey (what honest miners do at boot;
        makes signatures mandatory for these hotkeys in SignedTransport)."""
        for ident in self.identities:
            address_store.store_pubkey(ident.hotkey, ident.public_bytes)


# ---------------------------------------------------------------------------
# Open-loop serving load (the fleetsim observatory's latency harness)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class OpenLoopSpec:
    """One load point against the serving plane.

    OPEN loop: arrivals follow a seeded Poisson process that does NOT
    wait for completions — a closed-loop generator (submit, wait,
    repeat) self-throttles when the server saturates and therefore
    HIDES queueing collapse; the open-loop curve is the one where p99
    blows up when offered load crosses capacity (the Gemma-on-TPU
    serving comparison in PAPERS.md makes exactly this point). Prompt
    lengths are heavy-tailed (bounded Pareto), because uniform prompts
    understate paged-KV pressure.

    Latency is accounted in VIRTUAL milliseconds: every
    ``GenerationEngine.step`` advances the harness clock by ``step_ms``
    regardless of host speed, so the curve measures the SCHEDULER —
    admission, continuous batching, page allocation, preemption,
    queueing — deterministically (same seed, same spec => byte-equal
    load points), not the CI host's CPU. The real decode path still
    runs under it (real prefill/decode programs, real paged KV), which
    is what makes the scheduler's decisions real.
    """
    rate_rps: float
    duration_s: float = 8.0
    seed: int = 0
    min_prompt_tokens: int = 4
    max_prompt_tokens: int = 40
    tail_alpha: float = 1.6         # Pareto shape; smaller = heavier tail
    max_new_tokens: int = 16
    vocab: int = 128
    step_ms: float = 4.0            # virtual service time per engine step
    max_steps: int = 50_000         # collapse bound: stop, count unfinished

    def __post_init__(self):
        if self.rate_rps <= 0 or self.duration_s <= 0:
            raise ValueError("rate_rps and duration_s must be > 0")
        if not 1 <= self.min_prompt_tokens <= self.max_prompt_tokens:
            raise ValueError("need 1 <= min_prompt <= max_prompt")
        if self.tail_alpha <= 0 or self.step_ms <= 0:
            raise ValueError("tail_alpha and step_ms must be > 0")


def sample_arrivals(spec: OpenLoopSpec) -> list[tuple[float, list[int]]]:
    """The seeded arrival schedule: (arrival_time_s, prompt_tokens)
    pairs over ``duration_s``. Exponential inter-arrivals at
    ``rate_rps``; lengths are bounded Pareto over
    [min_prompt_tokens, max_prompt_tokens]."""
    rng = np.random.default_rng(spec.seed)
    out: list[tuple[float, list[int]]] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / spec.rate_rps))
        if t >= spec.duration_s:
            return out
        raw = spec.min_prompt_tokens * float(
            (1.0 - rng.random()) ** (-1.0 / spec.tail_alpha))
        n = int(min(max(raw, spec.min_prompt_tokens),
                    spec.max_prompt_tokens))
        prompt = rng.integers(1, spec.vocab, n).tolist()
        out.append((t, [int(x) for x in prompt]))


def run_open_loop(engine, spec: OpenLoopSpec, *,
                  prefill_busy_steps: int = 0) -> dict:
    """Drive one load point through a live GenerationEngine; returns the
    load-point record the fleetsim scorecard embeds.

    The loop submits every arrival whose (virtual) time has come —
    whether or not the engine has capacity — then takes one scheduler
    step and advances the virtual clock by ``step_ms``; when the engine
    goes idle before the next arrival, the clock jumps to it. TTFT is
    arrival -> first generated token, TPOT the gap between a request's
    consecutive tokens, both in virtual ms; ``unfinished`` counts
    requests still incomplete when the ``max_steps`` collapse bound
    stops the run — a nonzero value IS the queueing-collapse signal,
    alongside the exploding p99.

    ``prefill_busy_steps`` is the virtual-clock prefill cost model: each
    completed prefill charges that many extra BUSY ticks (clock advances,
    the engine does not step), so a long-prompt admission visibly stalls
    every in-flight decode on the same worker — the head-of-line effect
    disaggregated serving exists to remove. The default 0 preserves the
    legacy uniform-tick curve byte-for-byte."""
    from .obs import percentile

    if prefill_busy_steps < 0:
        raise ValueError("prefill_busy_steps must be >= 0")
    arrivals = sample_arrivals(spec)
    now = 0.0
    i = 0
    steps = 0
    debt = 0                        # busy ticks owed for finished prefills
    last_prefills = int(getattr(engine, "prefills_done", 0))
    tracked: list[dict] = []        # {req, arrival_s, seen, last_emit}
    ttft_ms: list[float] = []
    tpot_ms: list[float] = []

    def _submit_due() -> None:
        nonlocal i
        while i < len(arrivals) and arrivals[i][0] <= now:
            t_arr, prompt = arrivals[i]
            seq = i
            i += 1
            # deterministic content-addressable identity (arrival index
            # as the sequence salt): the same spec mints the same ids,
            # so a frozen tail exemplar can be named in a test
            req = engine.submit(
                prompt, spec.max_new_tokens,
                request_id=reqtrace.mint_request_id(
                    prompt, max_new_tokens=spec.max_new_tokens, seq=seq))
            tracked.append({"req": req, "arrival_s": t_arr,
                            "seen": 0, "last_emit": None})

    def _account() -> None:
        for rec in tracked:
            n = len(rec["req"].tokens)
            if n <= rec["seen"]:
                continue
            # a speculative step commits up to K+1 tokens in one tick;
            # spread their emission times evenly across the step so tpot
            # reflects the per-token pace, not a burst artifact. burst=1
            # (plain decode) reduces to the old single-emit bookkeeping.
            burst = n - rec["seen"]
            pace = spec.step_ms / 1e3 / burst
            for j in range(burst):
                t_emit = now - (burst - 1 - j) * pace
                if rec["last_emit"] is None:
                    ttft_ms.append((t_emit - rec["arrival_s"]) * 1e3)
                else:
                    tpot_ms.append((t_emit - rec["last_emit"]) * 1e3)
                rec["last_emit"] = t_emit
            rec["seen"] = n

    while (i < len(arrivals) or not engine.idle or debt > 0) \
            and steps < spec.max_steps:
        if engine.idle and debt == 0 and i < len(arrivals):
            now = max(now, arrivals[i][0])   # park until the next arrival
            _submit_due()
            continue
        _submit_due()
        if debt > 0:
            debt -= 1                       # engine busy with prefill math
        else:
            engine.step()
            done = int(getattr(engine, "prefills_done", 0))
            debt += (done - last_prefills) * prefill_busy_steps
            last_prefills = done
        steps += 1
        now += spec.step_ms / 1e3
        _account()

    completed = sum(1 for r in tracked if r["req"].done_evt.is_set())
    unfinished = len(tracked) - completed
    # a live run seals its trace reservoir on the way out so the tail
    # exemplars of even a sub-window run are frozen into the flight
    # recorder (scripts/request_report.py reads them from there)
    book = getattr(engine, "trace", None)
    pm_ref = book.seal_window() if book is not None else None

    def _pcts(vals: list[float]) -> dict:
        s = sorted(vals)
        return {"p50": round(percentile(s, 50.0), 3),
                "p95": round(percentile(s, 95.0), 3),
                "p99": round(percentile(s, 99.0), 3)}

    return {
        "rate_rps": spec.rate_rps,
        "duration_s": spec.duration_s,
        "offered": len(arrivals),
        "completed": completed,
        "unfinished": unfinished,
        "steps": steps,
        "virtual_s": round(now, 4),
        "trace_exemplars": (book.exemplars_frozen
                            if book is not None else 0),
        "trace_pm_ref": pm_ref,
        "tokens": int(sum(r["seen"] for r in tracked)),
        "ttft_ms": _pcts(ttft_ms) if ttft_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
        "tpot_ms": _pcts(tpot_ms) if tpot_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
    }


def run_open_loop_routed(engines, spec: OpenLoopSpec, *,
                         max_backend_queue: int = 6,
                         prefill_busy_steps: int = 0) -> dict:
    """One load point through N engines behind the router policy —
    the same virtual-clock discipline as :func:`run_open_loop` (every
    tick steps ALL engines; one tick is ``step_ms``), with the real
    :class:`~..engine.router.RouterPolicy` making the per-arrival
    spread/shed decision from each engine's live queue/active state.

    Shed arrivals are counted (``shed``) and EXCLUDED from the latency
    percentiles: the admission controller's contract is that admitted
    requests stay off the collapse curve, and a 429'd open-loop caller
    never waited in any queue. The offered/shed split plus the
    admitted-only p99 is exactly the curve FLEETSIM_r04 gates against
    the single-server r01 baseline.

    ``prefill_busy_steps`` charges the :func:`run_open_loop` prefill
    cost model per engine (default 0 = legacy uniform ticks)."""
    from ..engine.router import BackendState, RouterPolicy
    from .obs import percentile

    if prefill_busy_steps < 0:
        raise ValueError("prefill_busy_steps must be >= 0")
    policy = RouterPolicy(max_queue_depth=max_backend_queue)
    arrivals = sample_arrivals(spec)
    now = 0.0
    i = 0
    steps = 0
    shed = 0
    debt = [0] * len(engines)
    last_prefills = [int(getattr(e, "prefills_done", 0)) for e in engines]
    tracked: list[dict] = []
    ttft_ms: list[float] = []
    tpot_ms: list[float] = []
    states = [BackendState(url=f"engine://{n}", healthy=True)
              for n in range(len(engines))]

    def _submit_due() -> None:
        nonlocal i, shed
        while i < len(arrivals) and arrivals[i][0] <= now:
            t_arr, prompt = arrivals[i]
            i += 1
            for n, e in enumerate(engines):
                states[n].queue_depth = e.queue_depth
                states[n].active = e.active_count
            b = policy.choose(states)
            if b is None:
                shed += 1
                continue
            eng = engines[int(b.url.rsplit("/", 1)[-1])]
            req = eng.submit(
                prompt, spec.max_new_tokens,
                request_id=reqtrace.mint_request_id(
                    prompt, max_new_tokens=spec.max_new_tokens,
                    seq=i - 1))
            tracked.append({"req": req, "arrival_s": t_arr,
                            "seen": 0, "last_emit": None})

    def _account() -> None:
        for rec in tracked:
            n = len(rec["req"].tokens)
            if n <= rec["seen"]:
                continue
            # a speculative step commits up to K+1 tokens in one tick;
            # spread their emission times evenly across the step so tpot
            # reflects the per-token pace, not a burst artifact. burst=1
            # (plain decode) reduces to the old single-emit bookkeeping.
            burst = n - rec["seen"]
            pace = spec.step_ms / 1e3 / burst
            for j in range(burst):
                t_emit = now - (burst - 1 - j) * pace
                if rec["last_emit"] is None:
                    ttft_ms.append((t_emit - rec["arrival_s"]) * 1e3)
                else:
                    tpot_ms.append((t_emit - rec["last_emit"]) * 1e3)
                rec["last_emit"] = t_emit
            rec["seen"] = n

    while (i < len(arrivals) or any(debt)
           or not all(e.idle for e in engines)) and steps < spec.max_steps:
        if all(e.idle for e in engines) and not any(debt) \
                and i < len(arrivals):
            now = max(now, arrivals[i][0])
            _submit_due()
            continue
        _submit_due()
        for n, e in enumerate(engines):
            if debt[n] > 0:
                debt[n] -= 1            # busy with prefill math this tick
            elif not e.idle:
                e.step()
                done = int(getattr(e, "prefills_done", 0))
                debt[n] += (done - last_prefills[n]) * prefill_busy_steps
                last_prefills[n] = done
        steps += 1
        now += spec.step_ms / 1e3
        _account()

    completed = sum(1 for r in tracked if r["req"].done_evt.is_set())
    unfinished = len(tracked) - completed

    def _pcts(vals: list[float]) -> dict:
        s = sorted(vals)
        return {"p50": round(percentile(s, 50.0), 3),
                "p95": round(percentile(s, 95.0), 3),
                "p99": round(percentile(s, 99.0), 3)}

    return {
        "rate_rps": spec.rate_rps,
        "duration_s": spec.duration_s,
        "router": True,
        "servers": len(engines),
        "offered": len(arrivals),
        "routed": len(tracked),
        "shed": shed,
        "completed": completed,
        "unfinished": unfinished,
        "steps": steps,
        "virtual_s": round(now, 4),
        "tokens": int(sum(r["seen"] for r in tracked)),
        "ttft_ms": _pcts(ttft_ms) if ttft_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
        "tpot_ms": _pcts(tpot_ms) if tpot_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
    }


def run_open_loop_disagg(prefill_engines, decode_engines,
                         spec: OpenLoopSpec, *,
                         prefill_busy_steps: int = 0,
                         max_backend_queue: int = 6) -> dict:
    """One load point through a DISAGGREGATED fleet: arrivals land on a
    prefill-phase engine (chosen by the real router policy), which runs
    the bucketed prefill, emits the first token, and exports the KV
    pages as a content-addressed manifest; finished prefill legs are
    handed off to the least-loaded decode-phase engine carrying the
    ``kv_ref`` + first token, where the pages are adopted and decode
    streams under the paged-attention kernel. Same virtual-clock
    discipline as :func:`run_open_loop_routed`; ``prefill_busy_steps``
    charges the prefill cost model on EVERY engine (decode engines pay
    it only when a degraded transfer forces a local re-prefill), so the
    disaggregated and unified curves are comparable within one card.

    TTFT is arrival -> the prefill leg's first token; the decode leg
    re-emits that token verbatim, so accounting starts the decode leg
    at ``seen=1`` — no token is counted twice. ``handoffs`` counts
    prefill legs that carried a kv_ref; a failed export falls back to a
    plain decode-side submit (local prefill), keeping the harness
    lossless under the same no-flag-day contract as the router."""
    from ..engine.router import BackendState, RouterPolicy
    from .obs import percentile

    if prefill_busy_steps < 0:
        raise ValueError("prefill_busy_steps must be >= 0")
    policy = RouterPolicy(max_queue_depth=max_backend_queue)
    arrivals = sample_arrivals(spec)
    engines = list(prefill_engines) + list(decode_engines)
    now = 0.0
    i = 0
    steps = 0
    shed = 0
    handoffs = 0
    debt = [0] * len(engines)
    last_prefills = [int(getattr(e, "prefills_done", 0)) for e in engines]
    pending: list[dict] = []        # prefill legs in flight
    tracked: list[dict] = []        # decode legs (latency accounting)
    ttft_ms: list[float] = []
    tpot_ms: list[float] = []
    pre_states = [BackendState(url=f"engine://{n}", healthy=True,
                               phase="prefill")
                  for n in range(len(prefill_engines))]
    # engine counters are lifetime-cumulative; the load point reports
    # THIS run's deltas so warm engines can serve several rate points
    adopted0 = sum(int(getattr(e, "kv_adopted", 0))
                   for e in decode_engines)
    reprefill0 = sum(int(getattr(e, "kv_reprefills", 0))
                     for e in decode_engines)

    def _submit_due() -> None:
        nonlocal i, shed
        while i < len(arrivals) and arrivals[i][0] <= now:
            t_arr, prompt = arrivals[i]
            seq = i
            i += 1
            for n, e in enumerate(prefill_engines):
                pre_states[n].queue_depth = e.queue_depth
                pre_states[n].active = e.active_count
            b = policy.choose(pre_states)
            if b is None:
                shed += 1
                continue
            pe = prefill_engines[int(b.url.rsplit("/", 1)[-1])]
            rid = reqtrace.mint_request_id(
                prompt, max_new_tokens=spec.max_new_tokens, seq=seq)
            req = pe.submit(prompt, spec.max_new_tokens, request_id=rid)
            pending.append({"req": req, "arrival_s": t_arr,
                            "rid": rid, "prompt": prompt})

    def _handoff() -> None:
        nonlocal handoffs
        for rec in list(pending):
            req = rec["req"]
            if not req.done_evt.is_set():
                continue
            pending.remove(rec)
            ttft_ms.append((now - rec["arrival_s"]) * 1e3)
            de = min(decode_engines,
                     key=lambda e: e.queue_depth + e.active_count)
            if req.kv_ref is not None and req.tokens:
                handoffs += 1
                r2 = de.submit(rec["prompt"], spec.max_new_tokens,
                               request_id=rec["rid"], kv_ref=req.kv_ref,
                               first_token=int(req.tokens[0]))
            else:  # export failed: lossless fallback, local prefill
                r2 = de.submit(rec["prompt"], spec.max_new_tokens,
                               request_id=rec["rid"])
            tracked.append({"req": r2, "arrival_s": rec["arrival_s"],
                            "seen": 1, "last_emit": now})

    def _account() -> None:
        for rec in tracked:
            n = len(rec["req"].tokens)
            if n <= rec["seen"]:
                continue
            burst = n - rec["seen"]
            pace = spec.step_ms / 1e3 / burst
            for j in range(burst):
                t_emit = now - (burst - 1 - j) * pace
                tpot_ms.append((t_emit - rec["last_emit"]) * 1e3)
                rec["last_emit"] = t_emit
            rec["seen"] = n

    while (i < len(arrivals) or pending or any(debt)
           or not all(e.idle for e in engines)) and steps < spec.max_steps:
        if all(e.idle for e in engines) and not pending \
                and not any(debt) and i < len(arrivals):
            now = max(now, arrivals[i][0])
            _submit_due()
            continue
        _submit_due()
        for n, e in enumerate(engines):
            if debt[n] > 0:
                debt[n] -= 1            # busy with prefill math this tick
            elif not e.idle:
                e.step()
                done = int(getattr(e, "prefills_done", 0))
                debt[n] += (done - last_prefills[n]) * prefill_busy_steps
                last_prefills[n] = done
        steps += 1
        now += spec.step_ms / 1e3
        _handoff()
        _account()

    completed = sum(1 for r in tracked if r["req"].done_evt.is_set())
    unfinished = len(pending) + len(tracked) - completed

    def _pcts(vals: list[float]) -> dict:
        s = sorted(vals)
        return {"p50": round(percentile(s, 50.0), 3),
                "p95": round(percentile(s, 95.0), 3),
                "p99": round(percentile(s, 99.0), 3)}

    return {
        "rate_rps": spec.rate_rps,
        "duration_s": spec.duration_s,
        "disaggregated": True,
        "prefill_servers": len(prefill_engines),
        "decode_servers": len(decode_engines),
        "offered": len(arrivals),
        "routed": len(tracked) + len(pending),
        "shed": shed,
        "handoffs": handoffs,
        "kv_adopted": int(sum(getattr(e, "kv_adopted", 0)
                              for e in decode_engines)) - adopted0,
        "kv_reprefills": int(sum(getattr(e, "kv_reprefills", 0)
                                 for e in decode_engines)) - reprefill0,
        "completed": completed,
        "unfinished": unfinished,
        "steps": steps,
        "virtual_s": round(now, 4),
        "tokens": int(sum(r["seen"] for r in tracked)),
        "ttft_ms": _pcts(ttft_ms) if ttft_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
        "tpot_ms": _pcts(tpot_ms) if tpot_ms else
        {"p50": float("nan"), "p95": float("nan"), "p99": float("nan")},
    }
