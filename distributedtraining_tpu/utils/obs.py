"""Round-trip observability: spans, a phase-timing registry, correlation
ids, and anomaly-triggered profiler capture.

The reference ships flat per-role scalar logging (utils/mlflow_utils.py);
after the validator's fetch/eval pipeline and the miner's background
publish worker, the hot paths are asynchronous and cross-thread — a
regression in push latency or fetch staleness is invisible in flat logs.
This module is the one home of the structured layer every role emits:

- ``span("push.upload")`` context managers record start/duration records
  through the process's configured :class:`MetricsSink` (the same JSONL
  file the scalar metrics land in) and feed a latency histogram per span
  name. Spans nest; each record carries its parent and depth.
- a process-wide :class:`Registry` of counters and latency histograms
  (p50/p95/p99 from bounded ring reservoirs) with name linting —
  ``[a-z0-9_.]`` only, and one name cannot be both a counter and a
  histogram. ``flush()`` snapshots it through the sink at each role's
  natural cadence (miner log boundary, validator/averager round end).
- a **correlation id** per published artifact: the miner stamps
  ``delta_id`` into the delta's meta rider (transport/base.py), the
  validator and averager read it back and tag their fetch/screen/eval/
  merge spans with it — one artifact's life (snapshot -> upload ->
  fetch -> screen -> cohort-eval -> merge) is then reconstructable from
  the per-role JSONL files by ``scripts/obs_report.py``.
- :class:`AnomalyMonitor`: a loss spike, a push-failure streak, or a
  step-time p99 blowout arms a ONE-SHOT ``TraceCapture``
  (utils/metrics.py) so the profiler evidence of the first anomaly is on
  disk before anyone is paged.

Everything here is off unless a sink is configured (``configure``): the
module-level ``count``/``observe`` helpers and ``span`` are single-branch
no-ops when disabled, so instrumentation costs nothing in tests and
tight benches that never opt in (bench._time_metrics_overhead pins the
enabled cost: < 2% of step time).

Thread discipline: the registry and the span emitter are lock-protected
(the publish worker spans from its background thread while the train
loop spans concurrently); the span STACK and current correlation id are
thread-local, so a worker thread must re-enter its artifact's id via
``correlate(cid)`` — DeltaPublisher does exactly that.
"""

from __future__ import annotations

import contextlib
import logging
import math
import re
import threading
import time
from collections import deque
from typing import Any, Iterable

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")

# cap on a correlation id read back from a PEER-CONTROLLED rider
_CID_MAX_LEN = 120


def check_metric_name(name: str) -> str:
    """Registry name lint: reject anything outside ``[a-z0-9_.]`` before
    it reaches a backend (MLflow key rules, grep-ability, and the
    flattened ``<name>.p99`` snapshot spelling all assume it)."""
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"invalid metric name {name!r}: must match [a-z0-9_.]+")
    return name


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic float counter (thread-safe)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = check_metric_name(name)
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def merge_from(self, other: "Counter") -> None:
        """Fold another counter's total into this one (Registry.merge)."""
        self.inc(other.value)


class Gauge:
    """Last-value-wins gauge (thread-safe) — point-in-time levels the
    counter/histogram pair can't express: device memory watermarks, cache
    residency, fleet node counts. Snapshots as the bare name, like a
    counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = check_metric_name(name)
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def merge_from(self, other: "Gauge") -> None:
        """Last-merged-wins, matching the instrument's own semantics: the
        most recently merged registry's level is the one that survives
        (Registry.merge documents the ordering contract)."""
        self.set(other.value)


def percentile(sorted_vals, q: float) -> float:
    """numpy's default ('linear') percentile on an already-sorted list —
    implemented locally so the hot observability path never imports
    numpy (tests pin this against ``np.percentile``)."""
    n = len(sorted_vals)
    if n == 0:
        return float("nan")
    if n == 1:
        return float(sorted_vals[0])
    pos = (n - 1) * (q / 100.0)
    lo = math.floor(pos)
    hi = math.ceil(pos)
    frac = pos - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


class Histogram:
    """Latency histogram over a bounded ring reservoir (thread-safe).

    The ring keeps the most recent ``capacity`` observations — percentiles
    reflect CURRENT behavior, which is what an anomaly check wants (a
    classic reservoir sample would dilute a fresh regression with hours
    of healthy history). ``count``/``total`` are lifetime."""

    __slots__ = ("name", "capacity", "_ring", "_count", "_total", "_lock")

    def __init__(self, name: str, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = check_metric_name(name)
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring.append(float(value))
            self._count += 1
            self._total += float(value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return self._total

    def percentiles(self, qs: Iterable[float] = (50.0, 95.0, 99.0)
                    ) -> dict[str, float]:
        with self._lock:
            vals = sorted(self._ring)
        return {f"p{int(q)}": percentile(vals, q) for q in qs}

    def snapshot(self) -> dict[str, float]:
        out = {"count": float(self._count), "sum": self._total}
        if self._count:
            out.update(self.percentiles())
        return out

    def merge_from(self, other: "Histogram") -> None:
        """Fold another histogram in: lifetime count/sum add, and the
        other ring's observations extend this ring (still bounded by THIS
        ring's capacity — merging many actors keeps the newest tail, the
        same recency rule a single ring lives by)."""
        with other._lock:
            vals = list(other._ring)
            count, total = other._count, other._total
        with self._lock:
            self._ring.extend(vals)
            self._count += count
            self._total += total


class Registry:
    """Named counters + histograms; get-or-create, kind-checked.

    One name is ONE instrument: registering ``x`` as a counter after it
    exists as a histogram (or vice versa) raises — the duplicate-
    registration lint, so two call sites cannot silently split a metric
    into two series.

    ``max_names`` caps the metric-name CARDINALITY: once the registry
    holds that many distinct names, a request for a NEW name logs one
    warning, bumps ``dropped_names``, and returns a detached instrument
    (fully usable, never snapshotted) — callers keep working, the
    registry stays bounded. A 1000-actor fleet simulation
    (engine/fleetsim.py) hands every actor its own capped Registry, so
    one noisy actor cannot grow the process's metric vocabulary without
    bound. None (the default) keeps the historical unbounded behavior.

    ``merge(other)`` folds another registry in — counters add, gauges
    are last-merged-wins, histogram rings concatenate (bounded by the
    receiving ring's capacity) — which is how the fleet simulator
    assembles one scorecard registry from hundreds of per-actor ones.
    A kind mismatch between same-named instruments raises, the same
    duplicate-registration lint as ``_get``."""

    def __init__(self, *, max_names: int | None = None):
        if max_names is not None and max_names < 1:
            raise ValueError(f"max_names must be >= 1, got {max_names}")
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}
        self.max_names = max_names
        self.dropped_names = 0
        self._warned_cap = False

    def _get(self, name: str, kind) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                if self.max_names is not None \
                        and len(self._metrics) >= self.max_names:
                    self.dropped_names += 1
                    if not self._warned_cap:
                        self._warned_cap = True
                        logger.warning(
                            "registry at its %d-name cardinality cap; "
                            "dropping new metric %r (and any further new "
                            "names, counted in dropped_names)",
                            self.max_names, name)
                    return kind(name)  # detached: usable, never snapshotted
                m = self._metrics[name] = kind(name)
            elif not isinstance(m, kind):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def merge(self, other: "Registry") -> "Registry":
        """Fold ``other``'s instruments into this registry (see class
        docstring for per-kind semantics); returns self so scorecard
        assembly can chain ``reduce``-style. Names past this registry's
        cap are dropped-and-counted like any other new name."""
        with other._lock:
            items = list(other._metrics.items())
        for name, m in items:
            self._get(name, type(m)).merge_from(m)
        return self

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def peek(self, name: str) -> Any | None:
        """The registered instrument under ``name`` (or None) WITHOUT
        creating one — consumers that render a specific instrument's
        richer view (the exporter's labeled quantile gauges) must never
        mint empty series as a side effect of looking."""
        with self._lock:
            return self._metrics.get(name)

    def digest(self) -> str:
        """Short stable digest of the registered metric VOCABULARY (names,
        not values). Rides in heartbeats (engine/health.py) so a fleet
        report can flag nodes running a different instrumentation version
        — after an auto-update that renames metrics, aggregating their
        snapshots with the rest of the fleet's would silently compare
        different quantities."""
        import hashlib
        return hashlib.sha256(
            ",".join(self.names()).encode()).hexdigest()[:12]

    def __len__(self) -> int:
        return len(self._metrics)

    def snapshot(self) -> dict[str, float]:
        """Flat numeric dict: counters as ``name``, histograms as
        ``name.count/.sum/.p50/.p95/.p99`` — MLflow's numeric filter
        keeps every key, JSONL keeps the record verbatim."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict[str, float] = {}
        for name, m in items:
            if isinstance(m, (Counter, Gauge)):
                out[name] = m.value
            else:
                for k, v in m.snapshot().items():
                    out[f"{name}.{k}"] = v
        return out

    def flush_to(self, sink, *, step: int | None = None) -> dict[str, float]:
        snap = self.snapshot()
        if snap and sink is not None:
            sink.log(snap, step=step)
        return snap


# ---------------------------------------------------------------------------
# Process-wide state
# ---------------------------------------------------------------------------

class _ObsState:
    def __init__(self):
        self.registry = Registry()
        self.sink = None          # MetricsSink or None (None = disabled)
        self.role: str | None = None
        self.tl = threading.local()
        # attached FlightRecorder (utils/flight.py) or None: span closes,
        # registry flushes, and anomaly triggers mirror into its bounded
        # event ring. Held HERE (not imported) so obs stays import-light
        # and flight -> obs stays the only dependency direction.
        self.flight = None
        # attached device-observatory flush hook (utils/devprof.py) or
        # None: flush() mirrors the per-program device registry into the
        # same sink. Same held-not-imported rule as flight.
        self.devprof = None


_STATE = _ObsState()


def configure(sink, *, role: str | None = None) -> Registry:
    """Bind the process's span/metric emitter to ``sink`` (a MetricsSink).
    Called once per role boot (neurons/common.build); re-configuring
    replaces the sink/role and keeps the registry."""
    _STATE.sink = sink
    _STATE.role = role
    return _STATE.registry


def enabled() -> bool:
    return _STATE.sink is not None


def registry() -> Registry:
    return _STATE.registry


def current_sink():
    """The configured MetricsSink (or None) — the flight recorder logs
    frozen postmortem bundles through the same stream the spans ride."""
    return _STATE.sink


def attach_flight(recorder) -> None:
    """Attach (or detach, with None) a flight recorder (utils/flight.py):
    span closes, registry flushes, and anomaly triggers then mirror into
    its event ring. reset() drops the attachment with the rest of the
    process-wide state."""
    _STATE.flight = recorder


def attach_devprof(hook) -> None:
    """Attach (or detach, with None) the device observatory's flush hook
    (utils/devprof.on_flush): every flush() then mirrors the per-program
    device registry through the same sink as a ``{"devprof": ...}``
    record. devprof.enable() attaches itself; reset() drops it."""
    _STATE.devprof = hook


def reset() -> None:
    """Drop ALL global observability state (sink, role, registry, span
    stacks). Role entry points call this on exit so sequential in-process
    role runs (scripts/e2e_round.py, tests) never bleed metrics into each
    other; the tests/conftest.py guard asserts every test module leaves
    this state clean."""
    global _STATE
    _STATE = _ObsState()


def dirty() -> bool:
    """True when a sink is configured or the registry holds metrics —
    what the conftest hygiene guard checks after each test module."""
    return _STATE.sink is not None or len(_STATE.registry) > 0


def count(name: str, n: float = 1.0) -> None:
    """Increment a registry counter — single-branch no-op when disabled,
    so hot paths may call this unconditionally."""
    if _STATE.sink is None:
        return
    _STATE.registry.counter(name).inc(n)


def observe(name: str, value: float) -> None:
    """Record into a registry histogram — no-op when disabled."""
    if _STATE.sink is None:
        return
    _STATE.registry.histogram(name).observe(value)


def gauge(name: str, value: float) -> None:
    """Set a registry gauge — no-op when disabled."""
    if _STATE.sink is None:
        return
    _STATE.registry.gauge(name).set(value)


def registry_digest() -> str:
    return _STATE.registry.digest()


def flush(sink=None, *, step: int | None = None) -> dict[str, float]:
    """Snapshot the registry through ``sink`` (default: the configured
    one). The periodic-flush primitive each role calls at its natural
    cadence. Flush records carry an ``obs_registry`` role marker so
    offline joins (scripts/fleet_report.py) can attribute a snapshot to
    its emitting role without relying on file names."""
    if sink is None:
        sink = _STATE.sink
    if sink is None:
        return {}
    snap = _STATE.registry.snapshot()
    if snap:
        sink.log({"obs_registry": _STATE.role or "unknown", **snap},
                 step=step)
    fl = _STATE.flight
    if fl is not None:
        try:
            fl.on_flush(snap)
        except Exception:
            logger.exception("flight flush hook failed")
    dp = _STATE.devprof
    if dp is not None:
        try:
            dp(sink, _STATE.role)
        except Exception:
            logger.exception("devprof flush hook failed")
    return snap


# ---------------------------------------------------------------------------
# Correlation ids
# ---------------------------------------------------------------------------

def new_delta_id(miner_id: str, seq: int) -> str:
    """Deterministic per-push correlation id. Greppable, sortable, and
    collision-free per miner per process run; the push SEQUENCE (not a
    content hash) so superseded pushes stay distinguishable."""
    return f"{miner_id}-{seq:06d}"


def _tl():
    tl = _STATE.tl
    if not hasattr(tl, "stack"):
        tl.stack = []
        tl.cid = None
    return tl


def current_cid() -> str | None:
    return getattr(_STATE.tl, "cid", None)


@contextlib.contextmanager
def correlate(cid: str | None):
    """Set the CURRENT thread's correlation id for the duration — spans
    opened inside inherit it. The publish worker re-enters its job's id
    through this (thread-local state does not cross threads)."""
    tl = _tl()
    prev = tl.cid
    tl.cid = cid
    try:
        yield
    finally:
        tl.cid = prev


def capture_context() -> tuple:
    """Snapshot THIS thread's span context (open-span stack + current
    correlation id) for hand-off to a worker thread. Span state is
    thread-local by design (the publish worker re-enters its id via
    ``correlate``); a worker POOL that fans one caller's work across
    threads instead captures the submitting thread's context here and
    installs it per job via ``use_context`` — concurrent ``avg.fetch``
    spans then keep their parent nesting and inherited cid exactly as if
    they had run inline (engine/ingest.py's pool does this)."""
    tl = _tl()
    return (tuple(tl.stack), tl.cid)


@contextlib.contextmanager
def use_context(ctx: tuple | None):
    """Install a ``capture_context()`` snapshot on the CURRENT thread for
    the duration. The worker gets a private COPY of the captured stack:
    its spans nest under the submitter's open span without mutating the
    submitter's own (still live) stack across threads."""
    tl = _tl()
    prev_stack, prev_cid = tl.stack, tl.cid
    tl.stack = list(ctx[0]) if ctx else []
    tl.cid = ctx[1] if ctx else None
    try:
        yield
    finally:
        tl.stack, tl.cid = prev_stack, prev_cid


def rider_delta_id(meta: dict | None) -> str | None:
    """Defensive read of ``delta_id`` from a PEER-CONTROLLED meta rider:
    a short string or nothing (a hostile rider must not be able to
    inject junk into span records or report joins)."""
    if not isinstance(meta, dict):
        return None
    v = meta.get("delta_id")
    if isinstance(v, str) and 0 < len(v) <= _CID_MAX_LEN:
        return v
    return None


def fetch_cid(transport, miner_id: str) -> str | None:
    """Correlation id of ``miner_id``'s current artifact, from its meta
    rider — observability only, so every failure reads as None (riderless
    miners and transports without riders stay fully supported)."""
    if _STATE.sink is None:
        return None
    fm = getattr(transport, "fetch_delta_meta", None)
    if fm is None:
        return None
    try:
        return rider_delta_id(fm(miner_id))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def span(name: str, *, cid: str | None = None, **attrs):
    """Time a phase; on exit emit one record through the configured sink
    and feed the ``span.<name>_ms`` histogram. Nesting is tracked per
    thread (records carry ``parent`` and ``depth``). Zero-cost no-op when
    no sink is configured. ``attrs`` ride verbatim in the record (keep
    them JSON-able and small)."""
    st = _STATE
    if st.sink is None:
        yield
        return
    check_metric_name(name)
    tl = _tl()
    parent = tl.stack[-1] if tl.stack else None
    prev_cid = tl.cid
    if cid is not None:
        tl.cid = cid
    tl.stack.append(name)
    t0_wall = time.time()
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        dur_ms = (time.perf_counter() - t0) * 1e3
        tl.stack.pop()
        ccid = tl.cid
        tl.cid = prev_cid
        st.registry.histogram(f"span.{name}_ms").observe(dur_ms)
        rec = {"span": name, "dur_ms": round(dur_ms, 3), "t0": t0_wall,
               "depth": len(tl.stack)}
        if st.role is not None:
            rec["role"] = st.role
        if parent is not None:
            rec["parent"] = parent
        if ccid is not None:
            rec["cid"] = ccid
        if not ok:
            rec["error"] = True
        rec.update(attrs)
        try:
            st.sink.log(rec)
        except Exception:  # a broken sink must never break the traced phase
            logger.exception("span sink emit failed")
        fl = st.flight
        if fl is not None:
            try:
                fl.on_span(name, dur_ms, ccid, ok)
            except Exception:  # forensics must degrade, never break a phase
                logger.exception("flight span hook failed")


# ---------------------------------------------------------------------------
# Anomaly-triggered profiler capture
# ---------------------------------------------------------------------------

class AnomalyMonitor:
    """Arms a one-shot TraceCapture (utils/metrics.py) on the FIRST of:

    - loss spike: loss exceeds ``loss_spike_factor`` x its EMA (after
      ``loss_warmup`` observations), or goes non-finite;
    - push failure streak: ``push_failure_streak`` consecutive failed
      pushes with no success in between;
    - step-time p99 blowout: the recent-step p99 exceeds
      ``step_p99_factor`` x p50 (after ``step_warmup`` steps; checked
      every ``check_every`` observations so the per-step cost is one
      deque append).

    Exactly ONE arming per monitor lifetime, whatever fires afterwards —
    a capture window is expensive evidence, and the first anomaly is the
    one worth profiling. ``capture`` may be None (detection + counters
    only). The miner loop feeds observations and forwards ``tick()``."""

    def __init__(self, capture=None, *, loss_spike_factor: float = 2.0,
                 loss_warmup: int = 8, push_failure_streak: int = 3,
                 step_p99_factor: float = 8.0, step_warmup: int = 64,
                 check_every: int = 32, step_capacity: int = 256):
        if loss_spike_factor <= 1.0 or step_p99_factor <= 1.0:
            raise ValueError("anomaly factors must be > 1.0")
        if push_failure_streak < 1:
            raise ValueError("push_failure_streak must be >= 1")
        self.capture = capture
        self.loss_spike_factor = loss_spike_factor
        self.loss_warmup = loss_warmup
        self.push_failure_streak = push_failure_streak
        self.step_p99_factor = step_p99_factor
        self.step_warmup = step_warmup
        self.check_every = check_every
        self.triggered: str | None = None
        self._loss_ema: float | None = None
        self._loss_seen = 0
        self._fail_streak = 0
        self._last_pushes = 0
        self._last_failed = 0
        self._steps = Histogram("anomaly.step_ms", capacity=step_capacity)

    # -- observations -------------------------------------------------------
    def observe_loss(self, loss: float) -> None:
        loss = float(loss)
        if not math.isfinite(loss):
            self._trigger("loss_nonfinite", value=loss)
            return
        self._loss_seen += 1
        if self._loss_ema is None:
            self._loss_ema = loss
            return
        if (self._loss_seen > self.loss_warmup and self._loss_ema > 0
                and loss > self.loss_spike_factor * self._loss_ema):
            self._trigger("loss_spike", value=loss, ema=self._loss_ema)
        self._loss_ema += 0.1 * (loss - self._loss_ema)

    def observe_step_ms(self, ms: float) -> None:
        self._steps.observe(ms)
        n = self._steps.count
        if n < self.step_warmup or n % self.check_every:
            return
        p = self._steps.percentiles((50.0, 99.0))
        if p["p50"] > 0 and p["p99"] > self.step_p99_factor * p["p50"]:
            self._trigger("step_time_p99", p50=p["p50"], p99=p["p99"])

    def observe_push_counters(self, pushes: int, failed: int) -> None:
        """Feed the loop's cumulative MinerReport counters; deltas since
        the last call drive the streak (a success resets it)."""
        d_push = pushes - self._last_pushes
        d_fail = failed - self._last_failed
        self._last_pushes, self._last_failed = pushes, failed
        if d_push > 0:
            self._fail_streak = 0
        if d_fail > 0:
            self._fail_streak += d_fail
            if self._fail_streak >= self.push_failure_streak:
                self._trigger("push_failure_streak",
                              streak=self._fail_streak)

    def trigger_external(self, reason: str, **details) -> None:
        """Arm on an externally-detected anomaly — the fleet health
        plane's SLO breaches (engine/health.py) route through here so a
        stale miner or a fleet-wide loss divergence arms the SAME
        one-shot capture budget as the local detectors (first anomaly of
        any origin wins, forever)."""
        self._trigger(check_metric_name(reason), **details)

    # -- capture plumbing ---------------------------------------------------
    def tick(self) -> None:
        """Forward one step tick to the (possibly armed) capture."""
        if self.capture is not None:
            self.capture.tick()

    def close(self) -> None:
        if self.capture is not None:
            self.capture.close()

    def _trigger(self, reason: str, **details) -> None:
        if self.triggered is not None:
            return  # one-shot: first anomaly wins, forever
        self.triggered = reason
        count(f"obs.anomaly.{reason}")
        logger.warning("anomaly detected (%s%s)%s", reason,
                       "".join(f" {k}={v:.4g}" if isinstance(v, float)
                               else f" {k}={v}"
                               for k, v in details.items()),
                       "" if self.capture is None
                       else " — arming one-shot profiler capture")
        if _STATE.sink is not None:
            try:
                _STATE.sink.log({"anomaly": reason, **details})
            except Exception:
                logger.exception("anomaly sink emit failed")
        fl = _STATE.flight
        if fl is not None:
            try:
                fl.record("anomaly", reason=reason,
                          armed=self.capture is not None)
            except Exception:
                logger.exception("flight anomaly hook failed")
        if self.capture is not None:
            self.capture.arm()
