"""Observability: metric sinks with the reference's metric vocabulary.

The reference logs through MLflow (utils/mlflow_utils.py): per-role runs,
train loss every N steps, gradient staleness, per-hotkey validator scores,
merged-model loss/ppl, plus system metrics. Here a ``MetricsSink`` protocol
decouples engines from the backend: InMemory (tests), JSONL (always works,
zero deps), MLflow (optional, gated), and a TPU device-metrics helper
replacing ``torch.cuda.utilization``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Protocol


class MetricsSink(Protocol):
    def log(self, metrics: dict[str, Any], *, step: int | None = None) -> None: ...
    def log_params(self, params: dict[str, Any]) -> None: ...


class InMemorySink:
    def __init__(self):
        self.records: list[dict] = []
        self.params: dict[str, Any] = {}

    def log(self, metrics: dict[str, Any], *, step: int | None = None) -> None:
        self.records.append({"step": step, **metrics})

    def log_params(self, params: dict[str, Any]) -> None:
        self.params.update(params)


def jsonl_segments(path: str) -> list[str]:
    """Existing rotation segments of ``path``, OLDEST first, current file
    last — the read-side contract of :class:`JSONLSink` rotation. Readers
    (scripts/obs_report.py, scripts/fleet_report.py) concatenate these so
    a rotated soak run reads exactly like an unrotated one."""
    out = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        out.append(f"{path}.{n}")
        n += 1
    out.reverse()  # .N is the oldest, .1 the most recently rotated
    if os.path.exists(path) or not out:
        out.append(path)
    return out


class JSONLSink:
    """One JSON object per line; the default production sink.

    Thread-safe and crash-consistent: the publish worker
    (engine/publish.py) logs from its background thread while the train
    loop logs concurrently, so records are serialized under a lock and
    each is written as ONE ``write()`` call of a complete line to a
    handle kept open with line buffering (the old reopen-per-record
    spelling paid an open/close syscall pair per record and could
    interleave partial lines across threads). A reader that joins the
    file mid-crash sees whole records or nothing.

    ``max_bytes`` bounds the CURRENT file: once a write carries it past
    the limit, the file rotates (``path`` -> ``path.1`` -> ``path.2`` ...)
    and only the newest ``keep_segments`` rotated segments survive — a
    multi-day soak at second-scale cadences otherwise grows one multi-GB
    file (scripts/soak.py). Readers use :func:`jsonl_segments` to walk the
    rotation transparently. 0/None disables (the historical behavior).

    Rotation only size-bounds what THIS run writes; segments left by a
    previous run with a larger ``keep_segments`` (or a since-lowered
    config) would otherwise survive forever on long soak boxes. The lazy
    open therefore sweeps segments beyond ``retention_segments``
    (default: ``keep_segments``) once, before the first record lands —
    counted as ``obs.segments_pruned``."""

    def __init__(self, path: str, *, max_bytes: int | None = None,
                 keep_segments: int = 3,
                 retention_segments: int | None = None):
        self.path = path
        if max_bytes is not None and max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        if keep_segments < 1:
            raise ValueError(
                f"keep_segments must be >= 1, got {keep_segments}")
        if retention_segments is not None and retention_segments < 1:
            raise ValueError(f"retention_segments must be >= 1, "
                             f"got {retention_segments}")
        self.max_bytes = max_bytes or 0
        self.keep_segments = keep_segments
        self.retention_segments = retention_segments
        self.rotations = 0
        self.segments_pruned = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None  # opened lazily: no file until the first record
        self._written = None  # bytes in the current segment (lazy stat)

    def _sweep_locked(self) -> None:
        """Drop rotated segments beyond the retention bound (oldest-only
        by construction: ``path.N`` grows with age). A failed unlink
        stops the sweep — better a stale segment than a crashed sink."""
        keep = self.retention_segments or self.keep_segments
        n = keep + 1
        pruned = 0
        while True:
            seg = f"{self.path}.{n}"
            if not os.path.exists(seg):
                break
            try:
                os.remove(seg)
            except OSError:
                break
            pruned += 1
            n += 1
        if pruned:
            self.segments_pruned += pruned
            from . import obs
            obs.count("obs.segments_pruned", pruned)

    def log(self, metrics: dict[str, Any], *, step: int | None = None) -> None:
        rec = {"ts": time.time(), "step": step, **metrics}
        line = json.dumps(rec, default=float) + "\n"
        with self._lock:
            if self._fh is None:
                self._sweep_locked()
                self._fh = open(self.path, "a", buffering=1)
                if self.max_bytes:
                    self._written = self._fh.tell()  # append mode: resume
            self._fh.write(line)
            if self.max_bytes:
                self._written += len(line)
                if self._written >= self.max_bytes:
                    self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Shift path -> path.1 -> ... -> path.keep_segments (dropped).
        Whole-line writes + the atomic rename chain mean a concurrent
        reader sees complete segments or nothing torn."""
        self._fh.close()
        self._fh = None
        try:
            drop = f"{self.path}.{self.keep_segments}"
            if os.path.exists(drop):
                os.remove(drop)
            for n in range(self.keep_segments - 1, 0, -1):
                src = f"{self.path}.{n}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{n + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:  # a failed rotation must never lose records:
            pass         # keep appending to the oversized current file
        self._written = 0
        self.rotations += 1

    def log_params(self, params: dict[str, Any]) -> None:
        self.log({"params": params})

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()


class MLflowSink:
    """Optional MLflow backend (initialize_mlflow/log_model_metrics parity,
    utils/mlflow_utils.py:85-140). Constructing without mlflow installed or
    reachable raises; callers treat it as strictly optional, mirroring
    MLFLOW_ACTIVE=False in the reference (config/mlflow_config.py:3)."""

    def __init__(self, *, tracking_uri: str, experiment: str, run_name: str):
        import mlflow  # gated import
        self._mlflow = mlflow
        mlflow.set_tracking_uri(tracking_uri)
        mlflow.set_experiment(experiment)
        self._run = mlflow.start_run(run_name=run_name)

    def log(self, metrics: dict[str, Any], *, step: int | None = None) -> None:
        clean = {k: float(v) for k, v in metrics.items()
                 if isinstance(v, (int, float))}
        self._mlflow.log_metrics(clean, step=step)

    def log_params(self, params: dict[str, Any]) -> None:
        self._mlflow.log_params(params)


class _MultiSink:
    def __init__(self, sinks):
        self.sinks = list(sinks)

    def log(self, metrics, *, step=None):
        for s in self.sinks:
            s.log(metrics, step=step)

    def log_params(self, params):
        for s in self.sinks:
            s.log_params(params)


def multi_sink(*sinks: MetricsSink) -> MetricsSink:
    return _MultiSink(sinks)


# captures whose jax profiler is RUNNING — the tests/conftest.py hygiene
# guard asserts no test module leaves one behind (a leaked live profiler
# poisons every later capture in the process)
_LIVE_CAPTURES: "weakref.WeakSet[TraceCapture]" = weakref.WeakSet()


def live_captures() -> list["TraceCapture"]:
    return list(_LIVE_CAPTURES)


class TraceCapture:
    """Bounded ``jax.profiler`` trace capture for the perf loop (SURVEY §5).

    Captures exactly ``steps`` train steps into a TensorBoard-readable trace
    directory, then stops itself — the role keeps running at full speed.
    Poll ``tick()`` once per step from the training loop; it is a no-op
    after the capture window closes. Start is deferred to the first tick
    AFTER ``skip`` steps so compile time never pollutes the trace.

    ``arm=False`` constructs it DISARMED: ticks are free no-ops until
    ``arm()`` is called (the anomaly path, utils/obs.AnomalyMonitor —
    skip counts from the arming tick, so the capture window lands on the
    steps right after the anomaly fired). Arming is one-way and a
    finished capture can never re-arm: one window per instance.
    """

    def __init__(self, log_dir: str, *, steps: int = 5, skip: int = 3,
                 arm: bool = True):
        self.log_dir = log_dir
        self.steps = steps
        self.skip = skip
        self._armed = arm
        self._seen = 0
        self._active = False
        self._done = False
        self._jax = None  # cached on first armed tick (hot-loop: tick()
        #                   must not pay an import-system lookup per step)

    @property
    def armed(self) -> bool:
        return self._armed and not self._done

    def arm(self) -> None:
        if self._done or self._armed:
            return
        self._armed = True
        self._seen = 0

    def tick(self) -> None:
        if self._done or not self._armed:
            return
        if self._jax is None:
            import jax
            self._jax = jax
        self._seen += 1
        if not self._active and self._seen > self.skip:
            os.makedirs(self.log_dir, exist_ok=True)
            self._jax.profiler.start_trace(self.log_dir)
            self._active = True
            _LIVE_CAPTURES.add(self)
        elif self._active and self._seen > self.skip + self.steps:
            self._jax.profiler.stop_trace()
            self._active = False
            self._done = True
            _LIVE_CAPTURES.discard(self)

    def close(self) -> None:
        """Stop an in-flight capture (role shutdown mid-window)."""
        if self._active:
            if self._jax is None:  # pragma: no cover - active implies cached
                import jax
                self._jax = jax
            try:
                self._jax.profiler.stop_trace()
            finally:
                self._active = False
                self._done = True
                _LIVE_CAPTURES.discard(self)


_NET_BASELINE = None  # (bytes_sent, bytes_recv) at this process's first sample
# (psutil module, Process handle) once probed, False when unavailable —
# device_metrics runs inside hot loops at the log cadence, and the old
# spelling re-imported psutil and re-built the Process handle (a /proc
# walk) on every call
_PSUTIL_STATE = None


def _psutil_state():
    global _PSUTIL_STATE, _NET_BASELINE
    if _PSUTIL_STATE is None:
        try:
            import psutil
            proc = psutil.Process()
            psutil.cpu_percent()  # prime: first call always reads 0.0
            net = psutil.net_io_counters()
            if _NET_BASELINE is None:
                _NET_BASELINE = (net.bytes_sent, net.bytes_recv)
            _PSUTIL_STATE = (psutil, proc)
        except Exception:
            _PSUTIL_STATE = False
    return _PSUTIL_STATE


def device_memory_watermarks() -> dict[str, float]:
    """HBM watermarks aggregated across local devices, via JAX
    ``memory_stats`` — ``mem_in_use_bytes`` (max per-device bytes live
    now), ``mem_peak_bytes`` (max per-device high-water mark since start;
    the number that says whether the next model size fits), and
    ``mem_limit_bytes``. Silent empty dict when the backend exposes no
    stats (CPU), so callers can surface these as registry gauges
    unconditionally."""
    import jax
    out: dict[str, float] = {}
    for d in jax.local_devices():
        try:
            stats = getattr(d, "memory_stats", lambda: None)()
        except Exception:  # backends may raise instead of returning None
            stats = None
        if not stats:
            continue
        for key, name in (("bytes_in_use", "mem_in_use_bytes"),
                          ("peak_bytes_in_use", "mem_peak_bytes"),
                          ("bytes_limit", "mem_limit_bytes")):
            v = stats.get(key)
            if v:
                out[name] = max(out.get(name, 0.0), float(v))
    return out


def device_metrics() -> dict[str, float]:
    """TPU-side system metrics (replaces torch.cuda.utilization,
    utils/mlflow_utils.py:15-29): per-device HBM in use, via JAX
    memory_stats when the backend exposes it."""
    import jax
    out: dict[str, float] = {}
    for i, d in enumerate(jax.local_devices()):
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out[f"device{i}_bytes_in_use"] = float(stats.get("bytes_in_use", 0))
            peak = stats.get("peak_bytes_in_use")
            if peak:
                out[f"device{i}_peak_bytes"] = float(peak)
            lim = stats.get("bytes_limit")
            if lim:
                out[f"device{i}_mem_fraction"] = (
                    float(stats.get("bytes_in_use", 0)) / float(lim))
    # chain-RPC hygiene: live parked workers + lifetime timeouts
    # (utils/timeout.py) — a flaky substrate shows up here instead of as a
    # silent thread/socket leak
    from .timeout import abandoned_total, abandoned_workers
    out["chain_abandoned_workers"] = float(abandoned_workers())
    out["chain_abandoned_total"] = float(abandoned_total())
    state = _psutil_state()
    if state:
        psutil, proc = state
        try:
            out["cpu_percent"] = psutil.cpu_percent()
            out["rss_mb"] = proc.memory_info().rss / 1e6
            # net bytes parity (utils/mlflow_utils.py:15-69): on this
            # framework the network IS the artifact plane, so transfer
            # volume matters. psutil's counters are machine-wide since
            # boot; report the delta from this process's first sample so
            # runs are comparable (still host-wide — co-located traffic
            # is included, as in the reference)
            net = psutil.net_io_counters()
            out["net_sent_mb"] = (net.bytes_sent - _NET_BASELINE[0]) / 1e6
            out["net_recv_mb"] = (net.bytes_recv - _NET_BASELINE[1]) / 1e6
        except Exception:
            pass
    return out
