"""Request-scoped serving traces: the per-request causal story.

Every observability layer so far is AGGREGATE — registry histograms
(utils/obs.py), heartbeats (engine/health.py), devprof per-program
buckets — so a tail-latency request is invisible as a causal story:
WHICH of the five stacked per-token mechanisms (admission/shed,
prefix-cache reuse, paged decode, speculative accept/reject, hot swap)
made THIS request slow cannot be answered after the fact. This module
is the request-scoped layer (the TPU serving anatomy in PAPERS.md
2605.25645 argues ttft/tpot must decompose per phase to be actionable):

- every request gets a **content-addressable ``request_id``** minted at
  the frontend (:func:`mint_request_id` — a hash of the request content
  plus a per-process sequence, so identical retries stay
  distinguishable while the id remains reproducible from its inputs),
  propagated via the ``X-DT-Request-Id`` header through
  engine/router.py -> engine/serve.py -> engine/speculative.py; the
  disaggregated prefill/decode split (engine/kv_transfer.py) routes its
  cross-worker attribution through exactly this id — the ``kv_export``
  stage on the prefill worker and the ``kv_adopt`` stage on the decode
  worker share one request_id, so the waterfall shows the hop.
- each live request accumulates a **closed-vocabulary stage timeline**
  (:data:`STAGES`; :func:`check_stage` rejects unknown stages at the
  PRODUCER, exactly like flight.check_event_kind and the devprof
  program vocabulary — a lint test walks the wired modules' call
  sites). Recording is host-side only: one dict merge per slot per
  decode step, zero device work, no new jit programs — steady-state
  fresh compiles stay 0 and ``bench._time_serve`` A/Bs the overhead
  under 2%. Per-step stages (``decode``/``spec``/``cow``) COALESCE
  into batched entries so a 1000-token generation holds a bounded
  timeline, not a thousand rows.
- a **tail-exemplar reservoir** keeps the K slowest ttft/tpot requests
  per window and freezes their full timelines into the flight recorder
  (``serve.trace.exemplar`` / ``serve.trace.stage`` event kinds, one
  content-addressed bundle per sealed window, ``pm_ref`` linkage) —
  ``scripts/request_report.py --request-id`` renders the causal
  waterfall and the Chrome-trace export from exactly these events.
- finished/rejected outcomes feed the **SLO burn-rate monitor**
  (engine/health.py BurnRateMonitor) as the trace stream: ttft/tpot
  samples and shed verdicts, per request, on whatever clock the
  deployment runs (wall or fleetsim-virtual).

Off-by-default discipline: the engine only constructs a
:class:`TraceBook` when tracing is enabled, and every instrumentation
site is a single-branch no-op without one — the same contract as
utils/obs.py and utils/flight.py.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import threading
import time
from typing import Any, Sequence

from . import flight, obs

# ---------------------------------------------------------------------------
# The closed stage vocabulary
# ---------------------------------------------------------------------------

# stage -> description. docs/observability.md renders this table;
# tests/test_reqtrace.py lints every producer call site in the wired
# modules against these keys (the devprof/flight pattern). record()
# rejects anything else at the PRODUCER — a typo'd stage must fail in
# the first test that exercises the site, not silently fork the
# vocabulary.
STAGES: dict[str, str] = {
    "queue": "request entered the engine queue (submit); depth at entry",
    "admit": "slot granted; queue_age_ms = submit -> admission wait",
    "readmit": "re-admission after a preempt / swap-invalidate requeue",
    "prefill": "prompt prefill dispatched; pfx_hit, pfx_tokens, dur_ms",
    "decode": "plain decode steps this request rode (coalesced batch: "
              "n steps, tokens emitted)",
    "spec": "speculative rounds (coalesced batch: n rounds, proposed, "
            "accepted)",
    "spec_draft": "drafter rebuilt its context for this request "
                  "(cold catch-up prefill before proposing)",
    "cow": "copy-on-write page copies before a shared-page write "
           "(coalesced batch)",
    "kv_export": "prefill worker exported this request's KV pages as "
                 "content-addressed shards (disaggregated serving); "
                 "pages, ok, dur_ms",
    "kv_adopt": "decode worker adopted exported KV pages into its pool "
                "(the cross-worker hop); pages, dur_ms — a failed "
                "transfer shows as a plain 'prefill' instead (the "
                "degrade path)",
    "preempt": "preempted back to the queue on page exhaustion",
    "swap_invalidate": "requeued by a restart-policy base hot-swap",
    "emit": "terminal: finished; tokens, status, ttft_ms, tpot_ms",
    "shed": "refused 429 at admission control (never queued)",
    "drain": "refused 503 while a drain-policy swap is in flight",
}

# per-step stages that merge into one batched timeline entry (the
# "decode-step batches" discipline: bounded timelines however long the
# generation)
_COALESCE = frozenset(("decode", "spec", "cow"))

_MAX_STAGES = 64        # timeline rows per request (overflow is flagged)
_MAX_WINDOW = 4096      # finished traces held per reservoir window

REQUEST_ID_HEADER = "X-DT-Request-Id"

_SEQ = itertools.count()


def check_stage(stage: str) -> str:
    """Producer-side schema lint (the reqtrace twin of
    flight.check_event_kind): a stage outside the closed vocabulary
    must fail at the call site, not parse-time at every consumer."""
    if stage not in STAGES:
        raise ValueError(f"unknown reqtrace stage {stage!r}; expected "
                         f"one of {sorted(STAGES)}")
    return stage


def mint_request_id(content, *, seq: int | None = None, **meta) -> str:
    """Content-addressable request id: ``rq-`` + 16 hex of the sha256
    over the request content (token ids, raw body bytes, or text),
    its sampling meta, and a per-process sequence number. The sequence
    keeps identical retries distinguishable; given the same
    (content, meta, seq) the id is bit-reproducible — which is what
    lets a frontend, a router, and an offline report all derive the
    same identity for one request without coordination."""
    h = hashlib.sha256()
    if isinstance(content, (bytes, bytearray)):
        h.update(bytes(content))
    elif isinstance(content, str):
        h.update(content.encode())
    else:
        h.update(json.dumps([int(t) for t in content]).encode())
    if meta:
        h.update(json.dumps(
            {k: meta[k] for k in sorted(meta)}, default=float).encode())
    n = next(_SEQ) if seq is None else int(seq)
    h.update(str(n).encode())
    return "rq-" + h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# One request's timeline
# ---------------------------------------------------------------------------

class RequestTrace:
    """The stage timeline of ONE request. Mutated by the engine's step
    thread (every stage after ``queue``); built by the submit thread
    (which records ``queue`` before the request is ever visible to the
    scheduler), so no per-record locking is needed."""

    __slots__ = ("request_id", "rid", "t0", "stages", "status", "tokens",
                 "ttft_ms", "overflow", "_tpot_sum", "_tpot_n")

    def __init__(self, request_id: str, rid: int, t0: float):
        self.request_id = request_id
        self.rid = rid
        self.t0 = t0
        self.stages: list[dict] = []
        self.status = "live"
        self.tokens = 0
        self.ttft_ms: float | None = None
        self.overflow = 0
        self._tpot_sum = 0.0
        self._tpot_n = 0

    @property
    def tpot_ms(self) -> float | None:
        return self._tpot_sum / self._tpot_n if self._tpot_n else None

    def record(self, stage: str, t: float, **fields) -> None:
        check_stage(stage)
        last = self.stages[-1] if self.stages else None
        if last is not None and last["stage"] == stage \
                and stage in _COALESCE:
            # batched per step: consecutive decode/spec/cow entries
            # merge — numeric fields accumulate, the entry spans
            # [t, t_last] with n merged steps
            last["n"] += 1
            last["t_last"] = t
            for k, v in fields.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    last[k] = last.get(k, 0) + v
            return
        if len(self.stages) >= _MAX_STAGES:
            self.overflow += 1
            return
        self.stages.append({"stage": stage, "t": t, "t_last": t, "n": 1,
                            **fields})

    def record_span(self, stage: str, t0: float, t1: float, n: int,
                    **fields) -> None:
        """Fold an ALREADY-coalesced batch in: ``n`` steps spanning
        [t0, t1]. The lazy producer path — the engine's per-token hot
        loop bumps slot-local scalars and flushes one span here when
        the request's story moves on (another stage, finish)."""
        check_stage(stage)
        last = self.stages[-1] if self.stages else None
        if last is not None and last["stage"] == stage \
                and stage in _COALESCE:
            last["n"] += n
            last["t_last"] = t1
            for k, v in fields.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    last[k] = last.get(k, 0) + v
            return
        if len(self.stages) >= _MAX_STAGES:
            self.overflow += n
            return
        self.stages.append({"stage": stage, "t": t0, "t_last": t1,
                            "n": n, **fields})

    def seen(self, stage: str) -> bool:
        return any(e["stage"] == stage for e in self.stages)

    def note_latency(self, *, ttft_ms: float | None = None,
                     tpot_ms: float | None = None,
                     tpot_sum_ms: float | None = None,
                     tpot_n: int = 0) -> None:
        """Fold the per-token latency attribution the engine's _emit
        already computes (no second clock read on the hot path).
        ``tpot_sum_ms``/``tpot_n`` fold a slot-accumulated batch in one
        call — the lazy twin of per-token ``tpot_ms``."""
        if ttft_ms is not None:
            self.ttft_ms = float(ttft_ms)
        if tpot_ms is not None:
            self._tpot_sum += float(tpot_ms)
            self._tpot_n += 1
        if tpot_sum_ms is not None:
            self._tpot_sum += float(tpot_sum_ms)
            self._tpot_n += int(tpot_n)

    def as_record(self) -> dict:
        """JSON-able summary (tests / debugging; the flight freeze path
        serializes stage-by-stage instead)."""
        return {"request_id": self.request_id, "rid": self.rid,
                "t0": self.t0, "status": self.status,
                "tokens": self.tokens, "ttft_ms": self.ttft_ms,
                "tpot_ms": self.tpot_ms, "overflow": self.overflow,
                "stages": [dict(e) for e in self.stages]}


# ---------------------------------------------------------------------------
# The per-engine collector
# ---------------------------------------------------------------------------

class TraceBook:
    """Per-engine trace collector + tail-exemplar reservoir.

    Thread contract: ``start``/``reject`` may be called from HTTP
    handler threads (they only touch ``_live``/``_window`` under
    ``_lock``); ``stage``/``note_latency``/``finish`` run on the single
    scheduler thread. ``seal_window`` may be called from either (the
    engine's finish path auto-seals on window expiry; loadgen and role
    shutdown seal explicitly so a short live run still freezes its
    exemplars)."""

    def __init__(self, *, clock=time.time, exemplar_k: int = 4,
                 window_s: float = 30.0, burn=None):
        if exemplar_k < 1:
            raise ValueError(f"exemplar_k must be >= 1, got {exemplar_k}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.clock = clock
        self.exemplar_k = exemplar_k
        self.window_s = window_s
        self.burn = burn
        self._live: dict[int, RequestTrace] = {}
        self._window: list[RequestTrace] = []
        self._window_t0 = float(clock())
        self._lock = threading.Lock()
        self.started = 0
        self.finished = 0
        self.rejected = 0
        self.windows_sealed = 0
        self.exemplars_frozen = 0
        self.last_pm_ref: str | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self, req, *, depth: int = 0) -> RequestTrace:
        """Open a trace for a submitted request and record its ``queue``
        stage (``req`` is a serve.ServeRequest: needs .rid,
        .request_id, .submitted_t)."""
        t = float(req.submitted_t or self.clock())
        tr = RequestTrace(req.request_id or f"rq-rid{req.rid}", req.rid, t)
        tr.record("queue", t, depth=depth)
        with self._lock:
            self._live[req.rid] = tr
            self.started += 1
        return tr

    def stage(self, rid: int, stage: str, t: float | None = None,
              **fields) -> None:
        """Record one stage against a live request — single-branch
        no-op for untracked rids (requests submitted before tracing
        was enabled). ``t`` lets a batched caller hoist ONE clock read
        per step instead of one per slot (the decode hot path)."""
        tr = self._live.get(rid)
        if tr is None:
            return
        tr.record(stage, float(self.clock()) if t is None else t,
                  **fields)

    def stage_span(self, rid: int, stage: str, t0: float, t1: float,
                   n: int, **fields) -> None:
        """Record a producer-coalesced batch of ``n`` steps spanning
        [t0, t1] (see RequestTrace.record_span)."""
        tr = self._live.get(rid)
        if tr is not None:
            tr.record_span(stage, t0, t1, n, **fields)

    def seen(self, rid: int, stage: str) -> bool:
        tr = self._live.get(rid)
        return tr.seen(stage) if tr is not None else False

    def note_latency(self, rid: int, **kw) -> None:
        tr = self._live.get(rid)
        if tr is not None:
            tr.note_latency(**kw)

    def get(self, rid: int) -> RequestTrace | None:
        return self._live.get(rid)

    def finish(self, req, status: str) -> RequestTrace | None:
        """Close a request's trace: records the terminal ``emit`` stage,
        feeds the burn-rate monitor, and enters the trace into the
        current reservoir window (sealing the window first when it
        expired)."""
        with self._lock:
            tr = self._live.pop(req.rid, None)
        if tr is None:
            return None
        now = float(self.clock())
        tr.status = status
        tr.tokens = len(req.tokens)
        tr.record("emit", now, tokens=tr.tokens, status=status,
                  ttft_ms=tr.ttft_ms, tpot_ms=tr.tpot_ms)
        if self.burn is not None:
            try:
                self.burn.observe(now, ttft_ms=tr.ttft_ms,
                                  tpot_ms=tr.tpot_ms)
            except Exception:
                pass  # a broken monitor must never break serving
        with self._lock:
            self.finished += 1
            if len(self._window) < _MAX_WINDOW:
                self._window.append(tr)
        if now - self._window_t0 >= self.window_s:
            self.seal_window(now=now)
        return tr

    def reject(self, request_id: str | None, stage: str, **fields) -> str:
        """Record a request refused at admission control (``shed`` /
        ``drain``) — it never queued, so its whole timeline is the one
        refusal stage. Feeds the shed stream of the burn monitor.
        Returns the (possibly just-minted) request id."""
        check_stage(stage)
        now = float(self.clock())
        rid = request_id or mint_request_id(b"", t=round(now, 3))
        tr = RequestTrace(rid, -1, now)
        tr.record(stage, now, **fields)
        tr.status = stage
        with self._lock:
            self.rejected += 1
        if self.burn is not None:
            try:
                self.burn.observe(now, shed=True)
            except Exception:
                pass
        obs.count("serve.trace_rejects")
        return rid

    # -- the reservoir -------------------------------------------------------
    def _pick_exemplars(self, window: list[RequestTrace]
                        ) -> list[RequestTrace]:
        """The K slowest by ttft UNION the K slowest by tpot — the two
        tails a serving SLO decomposes into (a queue-bound request and
        a decode-bound request are different stories)."""
        k = self.exemplar_k
        by_ttft = sorted((t for t in window if t.ttft_ms is not None),
                         key=lambda t: -t.ttft_ms)[:k]
        by_tpot = sorted((t for t in window if t.tpot_ms is not None),
                         key=lambda t: -(t.tpot_ms or 0.0))[:k]
        out, seen = [], set()
        for tr in by_ttft + by_tpot:
            if id(tr) not in seen:
                seen.add(id(tr))
                out.append(tr)
        return out

    def seal_window(self, *, now: float | None = None,
                    reason: str = "trace_exemplar") -> str | None:
        """Close the current reservoir window: freeze the tail
        exemplars' full timelines into the flight recorder
        (``serve.trace.*`` events + one content-addressed bundle) and
        start a fresh window. Returns the bundle id (``pm_ref``) or
        None when there was nothing to freeze / no recorder."""
        now = float(self.clock()) if now is None else now
        with self._lock:
            window, self._window = self._window, []
            self._window_t0 = now
        if not window:
            return None
        self.windows_sealed += 1
        obs.count("serve.trace_windows")
        exemplars = self._pick_exemplars(window)
        if not exemplars or not flight.enabled():
            return None
        for tr in exemplars:
            self._freeze_one(tr)
        self.exemplars_frozen += len(exemplars)
        obs.count("serve.trace_exemplars", len(exemplars))
        ref = flight.freeze_and_publish(reason)
        if ref:
            self.last_pm_ref = ref
        return ref

    @staticmethod
    def _freeze_one(tr: RequestTrace) -> None:
        flight.record(
            "serve.trace.exemplar", request_id=tr.request_id, rid=tr.rid,
            t0=round(tr.t0, 6), status=tr.status, tokens=tr.tokens,
            ttft_ms=None if tr.ttft_ms is None else round(tr.ttft_ms, 3),
            tpot_ms=None if tr.tpot_ms is None else round(tr.tpot_ms, 3),
            stages=len(tr.stages), overflow=tr.overflow or None)
        for e in tr.stages:
            extra = {k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in e.items()
                     if k not in ("stage", "t", "t_last", "n")}
            # a stage that measured its own duration (prefill,
            # spec_draft) wins over the coalesced [t, t_last] span
            dur = extra.pop("dur_ms",
                            round((e["t_last"] - e["t"]) * 1e3, 3))
            flight.record(
                "serve.trace.stage", request_id=tr.request_id,
                stage=e["stage"], rel_ms=round((e["t"] - tr.t0) * 1e3, 3),
                dur_ms=dur, n=e["n"], **extra)

    # -- exposure ------------------------------------------------------------
    @property
    def live_count(self) -> int:
        return len(self._live)

    def counters(self) -> dict:
        """Numeric snapshot for healthz / heartbeat extras."""
        return {"trace_started": float(self.started),
                "trace_finished": float(self.finished),
                "trace_rejected": float(self.rejected),
                "trace_windows": float(self.windows_sealed),
                "trace_exemplars": float(self.exemplars_frozen)}
