"""JAX platform override for role subprocesses and harnesses.

Some environments force-select a platform from sitecustomize, ignoring the
``JAX_PLATFORMS`` env var — only a ``jax.config`` update wins (the same
mechanism tests/conftest.py uses). Every entry point calls this ONCE,
before its first backend touch.
"""

from __future__ import annotations

import os


def force_platform_from_env(var: str = "DT_FORCE_PLATFORM") -> str | None:
    """Apply ``$DT_FORCE_PLATFORM`` (e.g. "cpu") via jax.config; returns the
    applied platform or None. Must run before any JAX backend
    initialization — importing jax here is safe, initializing it is not."""
    val = os.environ.get(var)
    if val:
        import jax

        jax.config.update("jax_platforms", val)
    return val
