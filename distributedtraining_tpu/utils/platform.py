"""JAX platform override for role subprocesses and harnesses.

Some environments force-select a platform from sitecustomize, ignoring the
``JAX_PLATFORMS`` env var — only a ``jax.config`` update wins (the same
mechanism tests/conftest.py uses). Every entry point calls this ONCE,
before its first backend touch.
"""

from __future__ import annotations

import os


def force_platform_from_env(var: str = "DT_FORCE_PLATFORM",
                            *, honor_jax_platforms: bool = False
                            ) -> str | None:
    """Apply ``$DT_FORCE_PLATFORM`` (e.g. "cpu") via jax.config; returns the
    applied platform or None. Must run before any JAX backend
    initialization — importing jax here is safe, initializing it is not.

    ``honor_jax_platforms=True`` additionally treats ``JAX_PLATFORMS=cpu``
    as a CPU request (harness contract: the driver sets that env var, which
    the sitecustomize would otherwise override)."""
    val = os.environ.get(var)
    if not val and honor_jax_platforms \
            and os.environ.get("JAX_PLATFORMS") == "cpu":
        val = "cpu"
    if val:
        import jax

        jax.config.update("jax_platforms", val)
    return val
