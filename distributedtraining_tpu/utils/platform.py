"""JAX platform override for role subprocesses and harnesses.

Some environments force-select a platform from sitecustomize, ignoring the
``JAX_PLATFORMS`` env var — only a ``jax.config`` update wins (the same
mechanism tests/conftest.py uses). Every entry point calls this ONCE,
before its first backend touch.
"""

from __future__ import annotations

import os


def force_platform_from_env(var: str = "DT_FORCE_PLATFORM",
                            *, honor_jax_platforms: bool = False
                            ) -> str | None:
    """Apply ``$DT_FORCE_PLATFORM`` (e.g. "cpu") via jax.config; returns the
    applied platform or None. Must run before any JAX backend
    initialization — importing jax here is safe, initializing it is not.

    ``honor_jax_platforms=True`` additionally treats ``JAX_PLATFORMS=cpu``
    as a CPU request (harness contract: the driver sets that env var, which
    the sitecustomize would otherwise override)."""
    val = os.environ.get(var)
    if not val and honor_jax_platforms \
            and os.environ.get("JAX_PLATFORMS") == "cpu":
        val = "cpu"
    if val:
        import jax

        jax.config.update("jax_platforms", val)
    return val


def ensure_virtual_devices(n: int) -> None:
    """Guarantee XLA_FLAGS requests >= ``n`` host-platform devices.

    Must run before the first backend touch. An existing smaller count
    (stale operator env) is RAISED in place — appending a second flag
    instance would rely on unspecified last-wins parsing, and keeping
    the stale value fails later with a mesh-size error that never
    mentions the env var. Shared by the AOT scale artifact and the
    sharded E2E runners."""
    import re

    flag = "--xla_force_host_platform_device_count"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{flag}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {flag}={n}".strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(m.group(0), f"{flag}={n}")
