"""Flash-attention numerics on the real chip: forward AND grad parity vs the
dense oracle at T in {256, 1024}, packed segments included.

This is the on-device half of tests/test_flash_attention.py (whose kernel
parity cases skip under the CPU-forcing conftest). The +14%/+16% train-path
claims (models/gpt2.py) and the custom _block_sizes schedule
(ops/flash_attention.py) rest on these numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops.attention import causal_attention
from distributedtraining_tpu.ops.flash_attention import flash_attention


def _qkv(B=2, T=512, H=4, D=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
                 for _ in range(3))


def _segments(B, T, seed=1):
    """Block-constant packing ids, 128-aligned like data/packing.py output."""
    rng = np.random.default_rng(seed)
    seg = np.repeat(rng.integers(0, 3, (B, T // 128)), 128, axis=1)
    return jnp.asarray(np.sort(seg, axis=1), jnp.int32)  # monotone per row


@pytest.mark.parametrize("T", [256, 1024])
def test_forward_matches_dense(T):
    q, k, v = _qkv(T=T)
    out = flash_attention(q, k, v)
    assert out is not None, "kernel declined on TPU at a supported shape"
    ref = causal_attention(q, k, v, impl="dense")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.parametrize("T", [256, 1024])
def test_forward_matches_dense_packed(T):
    q, k, v = _qkv(T=T)
    seg = _segments(*q.shape[:2])
    out = flash_attention(q, k, v, segment_ids=seg)
    assert out is not None
    ref = causal_attention(q, k, v, segment_ids=seg, impl="dense")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.parametrize("T", [256, 1024])
@pytest.mark.parametrize("packed", [False, True])
def test_grads_match_dense(T, packed):
    q, k, v = _qkv(T=T)
    seg = _segments(*q.shape[:2]) if packed else None

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, segment_ids=seg)
                       .astype(jnp.float32) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(causal_attention(q, k, v, segment_ids=seg,
                                        impl="dense")
                       .astype(jnp.float32) ** 2)

    gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=1e-1, err_msg=f"d{name} mismatch (T={T}, packed={packed})")


def test_train_step_flash_vs_dense_loss():
    """One GPT-2 train step each way: the flash path's loss must track the
    dense path's (same init, same batch) — catches wiring bugs where the
    kernel silently drops masks."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    losses = {}
    for impl in ("flash", "dense"):
        # head_dim 64 + T 256: shapes the kernel accepts (a tinier config
        # would silently decline to dense and compare dense vs dense)
        cfg = gpt2.GPT2Config(vocab_size=512, n_positions=256, n_embd=256,
                              n_layer=2, n_head=4, vocab_multiple=128,
                              attention_impl=impl)
        model, cfg = gpt2.make_model(cfg)
        engine = TrainEngine(model, seq_len=256)
        state = engine.init_state(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 256)), jnp.int32)}
        _, m = engine.train_step(state, batch)
        losses[impl] = float(m["loss"])
    assert np.isfinite(losses["flash"])
    np.testing.assert_allclose(losses["flash"], losses["dense"], rtol=2e-2)


def test_fused_loss_matches_standard_on_chip():
    """Fused (tiled-head) CE vs the materialized-logits path on real
    hardware: loss parity through a full jitted train step at a kernel-
    relevant shape (head_dim 64, T 512)."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    cfg = gpt2.GPT2Config(vocab_size=50257, n_positions=512, n_embd=256,
                          n_layer=2, n_head=4, vocab_multiple=128)
    model, cfg = gpt2.make_model(cfg)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (4, 512)), jnp.int32)}
    losses = {}
    for fused in (False, True):
        engine = TrainEngine(model, seq_len=512, fused_loss=fused)
        state = engine.init_state(jax.random.PRNGKey(0))
        _, m = engine.train_step(state, batch)
        losses[fused] = float(m["loss"])
    assert np.isfinite(losses[True])
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-3)
