"""Dequant->scatter-add kernel numerics on the real chip.

The on-device half of tests/test_dequant_scatter.py: the real Mosaic
lowering of the in-place RMW scatter loop (VMEM-resident accumulator,
``input_output_aliases``) against the XLA scatter-add, and the
kernel-routed ``accumulate_delta`` against the densify reference. If
the probe declines here, ingest silently rides the XLA spelling — that
is a supported degrade, but this lane makes it LOUD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu.ops import dequant_scatter as dsc


def test_probe_decision_is_explicit():
    """Surface the probe verdict: xfail (not silent-pass) when Mosaic
    declines the scatter kernel on this chip/toolchain."""
    if not dsc._probe_ok():
        pytest.xfail("dequant-scatter kernel probe declined on this "
                     "TPU toolchain — ingest rides the XLA fallback")


def test_kernel_matches_xla_on_chip():
    if not dsc.enabled():
        pytest.skip("kernel probe declined")
    rng = np.random.default_rng(0)
    n, k = 1 << 16, 1024
    flat = jnp.asarray(rng.standard_normal(n), jnp.float32)
    idx = jnp.asarray(rng.integers(0, n, k), jnp.int32)
    for q in (jnp.asarray(rng.integers(-127, 128, k), jnp.int8),
              jnp.asarray(rng.standard_normal(k), jnp.float32)):
        out = dsc.dequant_scatter_add(flat, idx, q, 0.37)
        assert out is not None
        ref = flat.at[idx].add(q.astype(jnp.float32) * 0.37)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


def test_accumulate_delta_kernel_route_on_chip():
    if not dsc.enabled():
        pytest.skip("kernel probe declined")
    rng = np.random.default_rng(1)
    d = {"w": jnp.asarray(rng.standard_normal((512, 256)), jnp.float32),
         "b": jnp.asarray(rng.standard_normal((64,)), jnp.float32)}
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), d)
    packed, _ = dl.pack_delta_v2(d, density=1.0 / 32.0)
    acc0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros(np.shape(x), jnp.float32), template)
    got = dl.accumulate_delta(acc0, packed, 0.7)   # kernel route on TPU
    dense = dl.densify_packed_v2(packed, template)
    ref = dl.accumulate_delta(acc0, dense, 0.7)
    for k_ in d:
        np.testing.assert_allclose(np.asarray(got[k_]),
                                   np.asarray(ref[k_]), atol=1e-6)
