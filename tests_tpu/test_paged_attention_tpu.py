"""Paged-attention decode kernel numerics on the real chip.

The on-device half of tests/test_paged_attention.py (whose kernel cases
run interpreted under the CPU-forcing conftest): the REAL Mosaic
lowering — scalar-prefetched page tables driving per-page DMA, VMEM
scratch persistence across the streaming grid — against the XLA
reference at serving shapes, plus the engine-level greedy parity that
the serving plane's correctness contract rests on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops import paged_attention as pa


def _case(B, Hq, Hkv, D, P, MP, lens, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    pool = 1 + B * MP
    q = jnp.asarray(rng.standard_normal((B, 1, Hq, D)), dtype)
    kp = jnp.asarray(rng.standard_normal((pool, P, Hkv, D)), dtype)
    vp = jnp.asarray(rng.standard_normal((pool, P, Hkv, D)), dtype)
    kn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), dtype)
    vn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), dtype)
    pt = jnp.asarray(rng.integers(1, pool, (B, MP)), jnp.int32)
    sl = jnp.asarray(lens, jnp.int32)
    return q, kp, vp, pt, sl, kn, vn


def test_probe_passes_on_tpu():
    """The capability probe must accept the real chip — a silent decline
    would quietly serve every token off the XLA fallback."""
    assert pa._probe_ok(), "paged-attention kernel probe declined on TPU"


@pytest.mark.parametrize("shape", [
    (4, 8, 2, 64, 16, 8, [13, 127, 64, 1]),     # llama GQA, ragged
    (2, 4, 4, 64, 16, 8, [0, 128]),             # MHA, boundary lengths
    (8, 8, 2, 128, 16, 16, [100] * 8),          # D=128, multi-chunk
])
def test_kernel_matches_reference_on_chip(shape):
    B, Hq, Hkv, D, P, MP, lens = shape
    args = _case(B, Hq, Hkv, D, P, MP, lens)
    out = pa.paged_decode_attention(*args)
    assert out is not None, "kernel declined on TPU at a supported shape"
    ref = pa.paged_decode_reference(*args)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=1e-6)


def test_kernel_bf16_pages():
    """Production serving dtype: bf16 pages, fp32 softmax inside the
    kernel (flash-kernel tolerance, not f32 parity)."""
    args = _case(4, 8, 2, 64, 16, 8, [50, 3, 120, 77], dtype=jnp.bfloat16)
    out = pa.paged_decode_attention(*args)
    assert out is not None
    ref = pa.paged_decode_reference(*args)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


def test_engine_greedy_parity_on_chip():
    """The serving contract on real hardware: engine decode (kernel
    path) token-identical to the full-recompute oracle."""
    from distributedtraining_tpu.engine.serve import (GenerationEngine,
                                                      reference_generate)
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        dtype="float32", vocab_multiple=64))
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=n))
               for n in (5, 11)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=16)
    try:
        got = eng.generate(prompts, 8)
        assert got == [reference_generate(model, params, p, 8)
                       for p in prompts]
    finally:
        eng.close()
