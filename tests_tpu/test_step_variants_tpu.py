"""On-chip checks for the step/merge variants added in round 2.

Small configs (compile time, and large programs can wedge this rig's TPU
tunnel — see docs/perf.md): each case pins on-device agreement between a
variant and its reference spelling, not throughput.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distributedtraining_tpu import delta as delta_lib
from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.models import gpt2

SEQ = 128


def _batch(cfg, b=2, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (b, SEQ)), jnp.int32)}


def test_scan_blocks_loss_matches_unrolled_on_chip():
    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=SEQ)
    m1, _ = gpt2.make_model(cfg)
    m2, _ = gpt2.make_model(dataclasses.replace(cfg, scan_blocks=True))
    p1 = m1.init_params(jax.random.PRNGKey(0))
    e1 = TrainEngine(m1, seq_len=SEQ)
    e2 = TrainEngine(m2, seq_len=SEQ)
    s1 = e1.init_state(params=p1)
    s2 = e2.init_state(params=gpt2.stack_blocks(p1, cfg.n_layer))
    batch = _batch(cfg)
    _, l1 = e1.train_step(s1, batch)
    _, l2 = e2.train_step(s2, batch)
    np.testing.assert_allclose(float(l1["loss"]), float(l2["loss"]),
                               rtol=5e-3)  # bf16 compute


def test_accumulated_step_matches_full_batch_on_chip():
    """accum_steps=2 vs the full batch through the REAL jitted step.

    Params are compared under sgd(1.0), where params_before - params_after
    IS the gradient — comparing after an Adam step instead would amplify
    reduction-order rounding on any near-zero gradient into a full
    lr-sized difference (one bias-corrected Adam step is ~lr*sign(g)
    however small |g| is), which is what this test tripped over the first
    time it ever ran on hardware."""
    import optax

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=SEQ,
                              dtype="float32")
    model, _ = gpt2.make_model(cfg)
    p = model.init_params(jax.random.PRNGKey(0))
    # 'highest' forces true-f32 matmuls (bf16x6 passes): the TPU default
    # runs f32 matmuls as single-pass bf16 multiplies, which puts
    # reduction-order differences at bf16 scale (~4e-4 observed) and
    # drowns the summation-order property this test pins
    with jax.default_matmul_precision("highest"):
        e1 = TrainEngine(model, seq_len=SEQ, optimizer=optax.sgd(1.0))
        e2 = TrainEngine(model, seq_len=SEQ, optimizer=optax.sgd(1.0),
                         accum_steps=2)
        s1 = e1.init_state(params=p)
        s2 = e2.init_state(params=p)
        batch = _batch(cfg, b=4)
        s1, m1 = e1.train_step(s1, batch)
        s2, m2 = e2.train_step(s2, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-4)
    # identical math up to summation order: measured on-chip agreement is
    # ~3e-8 abs / ~8e-4 rel (near-zero grads); tolerances give ~3x margin
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-3, atol=1e-6)


def test_bf16_logits_loss_close_on_chip():
    """logits_dtype='bfloat16' on the real chip: same train-step loss to
    bf16 rounding (the MXU accumulation stays f32 either way)."""
    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=SEQ)
    m32, _ = gpt2.make_model(cfg)
    m16, _ = gpt2.make_model(
        dataclasses.replace(cfg, logits_dtype="bfloat16"))
    p = m32.init_params(jax.random.PRNGKey(0))
    e32 = TrainEngine(m32, seq_len=SEQ)
    e16 = TrainEngine(m16, seq_len=SEQ)
    batch = _batch(cfg)
    _, l32 = e32.train_step(e32.init_state(params=p), batch)
    _, l16 = e16.train_step(e16.init_state(params=p), batch)
    np.testing.assert_allclose(float(l16["loss"]), float(l32["loss"]),
                               rtol=1e-2)


def test_flat_merge_matches_leafwise_on_chip():
    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    leaves, treedef = jax.tree_util.tree_flatten(base)
    deltas = []
    for _ in range(4):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(leaves))
        deltas.append(jax.tree_util.tree_unflatten(
            treedef, [0.01 * jax.random.normal(kk, l.shape, l.dtype)
                      for kk, l in zip(ks, leaves)]))
    stacked = delta_lib.stack_deltas(deltas)
    w = jnp.asarray([0.4, 0.3, 0.2, 0.1])
    a = jax.jit(delta_lib.weighted_merge)(base, stacked, w)
    b = jax.jit(delta_lib.weighted_merge_flat)(base, stacked, w)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)


def test_pallas_fused_ce_matches_standard_on_chip():
    """The Pallas fused-CE kernels (ops/pallas_ce.py) on real hardware —
    the first Mosaic-lowered execution record for this kernel (interpret
    mode off-TPU cannot catch lowering bugs).

    Loss is pinned per-step; GRADIENTS are pinned through the full jitted
    step under sgd(1.0) (param diff == grad diff). Comparing params after
    an Adam step amplifies bf16 rounding on near-zero grads into lr-sized
    sign-flip differences (~lr*sign(g) per step) — the original spelling
    of this test, which failed on its first real-hardware run for exactly
    that reason while the kernel itself was numerically fine."""
    import optax

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=SEQ,
                              n_embd=128, n_head=4)
    model, _ = gpt2.make_model(cfg)
    p = model.init_params(jax.random.PRNGKey(0))
    std = TrainEngine(model, seq_len=SEQ, optimizer=optax.sgd(1.0))
    pal = TrainEngine(model, seq_len=SEQ, optimizer=optax.sgd(1.0),
                      fused_loss="pallas")
    s_std = std.init_state(params=p)
    s_pal = pal.init_state(params=p)
    first = True
    for seed in range(2):
        batch = _batch(cfg, seed=seed)
        s_std, m_std = std.train_step(s_std, batch)
        s_pal, m_pal = pal.train_step(s_pal, batch)
        np.testing.assert_allclose(float(m_pal["loss"]),
                                   float(m_std["loss"]), rtol=5e-3)
        if first:
            # two correct-but-different bf16 computations of the same
            # gradients (kernel recompute vs materialized logits): one
            # bf16 ulp of the largest params (~1e-3 abs measured on-chip);
            # checked after the FIRST step only — later steps legitimately
            # diverge as the parameter trajectories separate
            for a, b in zip(jax.tree_util.tree_leaves(s_std.params),
                            jax.tree_util.tree_leaves(s_pal.params)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-2, atol=2e-3)
            first = False


def test_pallas_sharded_ce_matches_unsharded_on_chip():
    """fused_ce_loss_sharded on a 1-device mesh vs the plain kernel: the
    shard_map spelling (all-gathers, row split, psum) must lower through
    Mosaic and agree with the unsharded path on real hardware. Multi-chip
    behavior is CPU-mesh-tested (tests/test_fused_loss.py); this pins the
    on-chip lowering of the same program."""
    import numpy as np
    from jax.sharding import Mesh

    from distributedtraining_tpu.ops.pallas_ce import (fused_ce_loss,
                                                       fused_ce_loss_sharded)

    rng = np.random.default_rng(0)
    B, T, E, V = 2, 64, 128, 384
    hidden = jnp.asarray(rng.normal(size=(B, T, E)), jnp.float32)
    head = jnp.asarray(rng.normal(size=(V, E)) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                ("dp", "fsdp", "tp"))

    def plain(h, w):
        return fused_ce_loss(h, w, labels)[0]

    def sharded(h, w):
        return fused_ce_loss_sharded(h, w, labels, mesh=mesh)[0]

    v0 = float(jax.jit(plain)(hidden, head))
    v1 = float(jax.jit(sharded)(hidden, head))
    np.testing.assert_allclose(v1, v0, rtol=1e-5)
    g0 = jax.jit(jax.grad(plain, argnums=(0, 1)))(hidden, head)
    g1 = jax.jit(jax.grad(sharded, argnums=(0, 1)))(hidden, head)
    for name, a, b in zip(("dh", "dw"), g1, g0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6, err_msg=name)
