"""On-device test lane (run on real TPU hardware; see scripts/run_tpu_tests.sh).

Unlike tests/conftest.py this does NOT force the CPU platform — the whole
point of this lane is to exercise the Pallas kernels on the hardware that
runs them in production (VERDICT r01: flash-attention numerics were never
verified on the device that runs them). Collection skips everything with a
clear message when no TPU is visible, so accidentally running this lane on
a CPU box is loud, not silently green.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() not in ("tpu", "axon"):
        skip = pytest.mark.skip(
            reason=f"tests_tpu/ needs TPU hardware; backend is "
                   f"{jax.default_backend()!r}")
        for item in items:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def tpu_device():
    return jax.devices()[0]
